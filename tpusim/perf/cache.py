"""Content-addressed :class:`EngineResult` cache.

Accel-Sim caches parsed kernel traces per launch because trace-driven
replay re-executes identical kernels thousands of times
(``trace_driven.cc:540-586``); tpusim's equivalent hot loop is the
schedule-walking engine re-pricing identical *modules* — a 64-link fault
sweep replays the same healthy kernels once per scenario, a tuner run
once per candidate config.  This module memoizes the priced result under
a key built from everything that can change the price, and nothing else:

    (module fingerprint, SimConfig fingerprint, arch name,
     timing-model version, (clock_scale, hbm_scale) [, topology sig])

The topology component is included **only for modules that contain
collective ops** — a collective-free kernel prices identically on any
pod, faulted or not, which is exactly why a link sweep can stop
re-pricing the healthy-kernel class (the double-pricing fix in
``tpusim.faults.sweep``).

Tiers:

* in-memory — an LRU dict (the per-process sweep/tuner win);
* on-disk (opt-in, ``--result-cache[=DIR]``, default ``.tpusim_cache/``)
  — JSON records with ``format_version``, written atomically
  (temp + ``os.replace``), invalidated by construction on any
  timing-model edit because :func:`~tpusim.timing.model_version.
  model_version` is baked into the key (a bumped model simply never
  matches the old files).  A corrupted/truncated record degrades to a
  recompute with a warning, never an error.

Determinism contract: a cache hit returns the exact float-for-float
result the engine would have produced — serialization round-trips
every counter through JSON's shortest-repr floats — so cached replays
reproduce golden stats byte-for-byte.  Results that carry run-scoped
state (obs samplers, recorded timelines) are never cached;
:class:`CachedEngine` bypasses the cache entirely for those runs.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
import threading
import warnings
from collections import OrderedDict, defaultdict
from pathlib import Path

from tpusim.obs.hub import NULL_OBS
from tpusim.timing.config import SimConfig
from tpusim.timing.engine import Engine, EngineResult
from tpusim.timing.model_version import model_version

__all__ = [
    "CACHE_FORMAT_VERSION",
    "CachedEngine",
    "DEFAULT_CACHE_DIR",
    "ResultCache",
    "as_result_cache",
    "clear_compiled_cache",
    "compiled_cache_stats",
    "compiled_for",
    "compiled_key_str",
    "set_compiled_cache_max",
    "config_fingerprint",
    "module_fingerprint",
    "module_uses_ici",
    "result_from_doc",
    "result_to_doc",
    "topology_signature",
]

CACHE_FORMAT_VERSION = 1

#: the ``--result-cache`` flag's bare form resolves here (cwd-relative,
#: like the reference's run-dir artifacts)
DEFAULT_CACHE_DIR = ".tpusim_cache"

_REPO = Path(__file__).resolve().parents[2]

#: sources OUTSIDE the timing model that still determine how hashed
#: module text prices: the IR and the parsers that build it (free-op
#: sets, trip-count extraction, layout/shape decoding, the C++
#: scanner).  model_version() deliberately covers only the timing
#: sources (it stamps correlation artifacts); the cache must also
#: invalidate on parser changes or a fixed parser would keep serving
#: pre-fix numbers from old disk records.
_PARSER_FILES: tuple[str, ...] = (
    "tpusim/ir.py",
    "tpusim/trace/hlo_text.py",
    "tpusim/trace/native.py",
    "tpusim/trace/lazy.py",
    "tpusim/trace/loop_analysis.py",
    "tpusim/trace/format.py",
    "native/hlo_scan.cpp",
    # the pricing fastpath is byte-identical to the engine BY CONTRACT,
    # but a contract is not a key: an edit that (wrongly or rightly)
    # shifts compiled pricing must orphan old disk records rather than
    # serve pre-edit bytes forever
    "tpusim/fastpath/compile.py",
    "tpusim/fastpath/price.py",
    "tpusim/fastpath/native.py",
    "tpusim/fastpath/batch.py",
    "tpusim/fastpath/jax_backend.py",
    "native/op_price.cpp",
)

_parser_version_cache: str | None = None


def parser_version() -> str:
    """Digest of the IR/parser sources (computed once per process)."""
    global _parser_version_cache
    if _parser_version_cache is None:
        h = hashlib.sha256()
        for rel in _PARSER_FILES:
            p = _REPO / rel
            h.update(rel.encode())
            h.update(b"\0")
            h.update(p.read_bytes() if p.is_file() else b"")
            h.update(b"\0")
        _parser_version_cache = h.hexdigest()[:16]
    return _parser_version_cache


# ---------------------------------------------------------------------------
# Fingerprints
# ---------------------------------------------------------------------------


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:24]


#: OSError errnos that mean the durable tier's medium is gone (full,
#: failing, or read-only) — one more write will not fare better, so the
#: store disables its write path for the instance's lifetime instead of
#: warning on every request (ENOSPC/EIO graceful degradation; shared by
#: the compile store and the hot-response cache)
FATAL_WRITE_ERRNOS = frozenset({
    errno.ENOSPC, errno.EDQUOT, errno.EIO, errno.EROFS,
})


def fatal_write_disable(exc: OSError, message: str) -> bool:
    """The shared disable decision of the three durable tiers: when
    ``exc`` is a medium-level failure, emit the single disable warning
    (``message``, each tier's own wording) and return True — the caller
    sets its instance flag and stops writing.  Non-fatal errnos return
    False and the caller keeps its pre-existing behavior."""
    if exc.errno not in FATAL_WRITE_ERRNOS:
        return False
    warnings.warn(message, RuntimeWarning, stacklevel=3)
    return True


def _stage_write(tmp: Path, text: str, durable: bool) -> None:
    """Stage one record's bytes to its temp file (the injection seam
    the ENOSPC regression tests monkeypatch)."""
    with open(tmp, "w") as f:
        f.write(text)
        if durable:
            f.flush()
            os.fsync(f.fileno())


def module_fingerprint(module) -> str | None:
    """Content digest of one module.

    ``load_trace`` stamps ``meta["content_hash"]`` from the on-disk HLO
    text (the cheap, canonical source); modules built in memory fall
    back to a structural walk over their ops.  Lazy modules hash their
    raw text directly — fingerprinting must not force a full parse.
    Returns None when no stable fingerprint exists (caching is then
    skipped for that module, never wrong)."""
    cached = getattr(module, "_fingerprint_cache", None)
    if cached is not None:
        return cached
    fp = None
    content = module.meta.get("content_hash") if module.meta else None
    if content:
        fp = str(content)
    else:
        text = getattr(module, "_text", None)  # LazyModuleTrace
        if isinstance(text, str):
            fp = _sha(text)
        else:
            try:
                fp = _structural_fingerprint(module)
            except (AttributeError, TypeError):
                fp = None
    try:
        module._fingerprint_cache = fp
    except (AttributeError, TypeError):
        pass
    return fp


def _structural_fingerprint(module) -> str:
    h = hashlib.sha256()
    h.update(module.name.encode())
    for cname in sorted(module.computations):
        comp = module.computations[cname]
        h.update(b"\0c")
        h.update(cname.encode())
        for op in comp.ops:
            h.update(b"\0o")
            h.update(
                f"{op.name}|{op.opcode}|{op.result}|{op.operands}|"
                f"{sorted(op.attrs.items()) if op.attrs else ''}".encode()
            )
    return h.hexdigest()[:24]


#: collective base opcodes whose presence makes a module's price
#: topology-dependent; used for the cheap raw-text scan on lazy modules
_COLLECTIVE_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)


def module_uses_ici(module) -> bool:
    """Does pricing this module consult the topology (any collective op)?

    Conservative for lazy modules: a raw-text marker scan may over-match
    (a comment mentioning ``all-reduce``), which only narrows cache
    sharing — it can never produce a wrong hit."""
    cached = getattr(module, "_uses_ici_cache", None)
    if cached is not None:
        return cached
    text = getattr(module, "_text", None)
    if isinstance(text, str):
        uses = any(m in text for m in _COLLECTIVE_MARKERS)
    else:
        uses = any(op.is_collective for op in module.all_ops())
    try:
        module._uses_ici_cache = uses
    except (AttributeError, TypeError):
        pass
    return uses


def config_fingerprint(config: SimConfig) -> str:
    """Digest of the fully-composed config (arch preset + tuned overlay
    + explicit overlays all flattened — frozen dataclasses serialize
    deterministically).  Memoized on the instance: SimConfig is frozen,
    and ``dataclasses.asdict``'s deep copy is expensive enough to
    dominate a warm fastpath replay if recomputed per run."""
    cached = config.__dict__.get("_fingerprint_memo") \
        if hasattr(config, "__dict__") else None
    if cached is not None:
        return cached
    doc = dataclasses.asdict(config)
    fp = _sha(json.dumps(doc, sort_keys=True, default=str))
    try:
        object.__setattr__(config, "_fingerprint_memo", fp)
    except (AttributeError, TypeError):
        pass
    return fp


def topology_signature(topo) -> str | None:
    """Stable signature of a (possibly faulted) topology, or None when
    the attached fault view cannot be fingerprinted (caching skipped)."""
    if topo is None:
        return "none"
    sig = f"{topo.dims}|{topo.wrap}"
    faults = getattr(topo, "faults", None)
    if faults is not None:
        fsig = getattr(faults, "signature", None)
        if fsig is None:
            return None
        sig += f"|f{fsig}"
    return sig


# ---------------------------------------------------------------------------
# EngineResult (de)serialization
# ---------------------------------------------------------------------------

#: dict-valued counter fields restored as defaultdict(float)
_FLOAT_MAP_FIELDS = (
    "unit_busy_cycles", "opcode_cycles", "per_op_cycles", "per_op_count",
    "per_op_hbm_bytes", "per_op_flops", "per_op_mxu_flops",
)
#: dict-valued fields restored as plain dicts
_PLAIN_MAP_FIELDS = ("per_op_opcode", "per_op_async")
#: run-scoped fields that are never cached
_UNCACHED_FIELDS = ("timeline", "samples")


def result_to_doc(result: EngineResult) -> dict:
    """JSON-safe document for one result; every counter field of the
    dataclass is carried explicitly so a future field addition changes
    the document shape (and old records stop matching) instead of
    silently dropping data."""
    doc: dict = {}
    for f in dataclasses.fields(EngineResult):
        if f.name in _UNCACHED_FIELDS:
            continue
        value = getattr(result, f.name)
        doc[f.name] = dict(value) if isinstance(value, dict) else value
    return doc


def result_from_doc(doc: dict) -> EngineResult:
    expected = {
        f.name for f in dataclasses.fields(EngineResult)
        if f.name not in _UNCACHED_FIELDS
    }
    if set(doc) != expected:
        raise ValueError(
            f"cache record field mismatch: {sorted(set(doc) ^ expected)}"
        )
    result = EngineResult()
    for name, value in doc.items():
        if name in _FLOAT_MAP_FIELDS:
            value = defaultdict(float, value)
        elif name in _PLAIN_MAP_FIELDS:
            value = dict(value)
        setattr(result, name, value)
    return result


# ---------------------------------------------------------------------------
# The cache
# ---------------------------------------------------------------------------


class ResultCache:
    """Two-tier content-addressed cache; see the module docstring.

    One instance may be shared across many drivers/engines (the sweep's
    per-link drivers all thread the same cache) — hit/miss counters are
    therefore cumulative over the instance's lifetime."""

    def __init__(
        self,
        disk_dir: str | Path | None = None,
        max_entries: int = 1024,
        obs=None,
        durable: bool = False,
        quota_bytes: int | None = None,
        quota_entries: int | None = None,
    ):
        self.disk_dir = Path(disk_dir) if disk_dir else None
        self.max_entries = max(int(max_entries), 1)
        # tpusim.guard: byte/count quota on the disk tier.  None = the
        # pre-guard unbounded behavior (zero added work, zero added
        # stats keys).  With a quota, every put that pushes the store's
        # estimated size past it triggers a crash-safe LRU GC
        # (whole-record deletes by mtime; disk hits touch mtime so
        # recency is usage, not write order) — safe under a daemon +
        # N forked workers sharing the dir because deletes are
        # idempotent and reads treat a vanished file as a plain miss.
        self.quota_bytes = int(quota_bytes) if quota_bytes else None
        self.quota_entries = int(quota_entries) if quota_entries else None
        # local running estimate of the store size; refreshed to the
        # authoritative scan on every GC.  Own puts only — a peer's
        # puts trigger the peer's own GC.
        self._disk_bytes_est: int | None = None
        self._disk_entries_est: int = 0
        # durable=True fsyncs each record (and its directory entry)
        # before the atomic publish.  The plain mode is already safe
        # against torn FILES (temp + os.replace); durability closes the
        # host-crash window where the rename survives but the data
        # blocks do not — a short-read record every later reader would
        # warn about.  The serve v2 worker fleet writes its shared L2
        # through this, so a worker killed mid-publish (or a node dying
        # under the pool) never poisons the tier for the survivors.
        self.durable = bool(durable)
        self.obs = obs if obs is not None else NULL_OBS
        self._mem: OrderedDict[str, EngineResult] = OrderedDict()
        # the serving daemon shares one instance across request threads;
        # the lock covers the LRU mutations (move_to_end racing an
        # eviction would KeyError), not the disk tier (atomic writes)
        self._lock = threading.Lock()
        # versions are captured once: a key is a statement about the
        # code that computed the result, not about when it is read.
        # model_version covers the timing sources; parser_version covers
        # the IR/parsers that turn hashed text into the priced program.
        self._model_version = f"{model_version()}+{parser_version()}"
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.disk_hits = 0
        self.disk_errors = 0
        # ENOSPC/EIO graceful degradation: once a staging write fails
        # with a medium-level errno, this instance stops writing (one
        # warning ever) and keeps serving from memory + existing disk
        # records — a full disk must never crash or spam a serving run
        self._disk_write_disabled = False
        # tpusim.guard accounting
        self.quarantined = 0
        self.gc_runs = 0
        self.gc_deleted = 0
        self.gc_freed_bytes = 0
        self.lru_shrinks = 0

    # -- keys ----------------------------------------------------------------

    def key_for(
        self,
        module,
        config: SimConfig,
        scales: tuple[float, float] = (1.0, 1.0),
        topology=None,
    ) -> str | None:
        """The content-addressed key, or None when this (module, run)
        cannot be cached safely."""
        mfp = module_fingerprint(module)
        if mfp is None:
            return None
        topo_part = "-"
        if module_uses_ici(module):
            topo = topology
            if topo is None:
                from tpusim.ici.topology import torus_for

                topo = torus_for(module.num_devices, config.arch.name)
            topo_part = topology_signature(topo)
            if topo_part is None:
                return None
        # capture-time platform joins the key: the cost model normalizes
        # capture-backend dtypes on module.meta["platform"], so identical
        # HLO text captured on cpu vs tpu prices differently
        platform = str(module.meta.get("platform", "")) if module.meta \
            else ""
        key = "|".join((
            mfp,
            f"p={platform}",
            config_fingerprint(config),
            config.arch.name,
            self._model_version,
            f"{scales[0]!r},{scales[1]!r}",
            topo_part,
        ))
        if getattr(module, "stream_lean", False):
            # streaming-lean results carry no per-op aggregates; they
            # must never cross-serve a full-fidelity consumer
            key += "|lean"
        return key

    # -- lookup / insert -----------------------------------------------------

    def get(self, key: str) -> EngineResult | None:
        with self._lock:
            result = self._mem.get(key)
            if result is not None:
                self._mem.move_to_end(key)
                self.hits += 1
        if result is not None:
            if self.disk_dir is not None and (
                self.quota_bytes is not None
                or self.quota_entries is not None
            ):
                # under a quota, an in-memory hit is still USAGE of the
                # durable record: without the touch, a record a hot L1
                # serves for hours looks oldest-mtime to every peer's
                # GC and the hottest key dies first (then a watchdog
                # shrink or worker recycle turns it into a recompute).
                # Un-governed stores skip the syscall (zero added work).
                try:
                    os.utime(self._path_for(key))
                except OSError:
                    pass  # evicted by a peer / read-only: plain aging
            self.obs.counter_add("cache.hits")
            return result
        if self.disk_dir is not None:
            result = self._disk_get(key)
            if result is not None:
                self._mem_put(key, result)
                self.hits += 1
                self.disk_hits += 1
                self.obs.counter_add("cache.hits")
                self.obs.counter_add("cache.disk_hits")
                return result
        self.misses += 1
        self.obs.counter_add("cache.misses")
        return None

    def put(self, key: str, result: EngineResult) -> None:
        self._mem_put(key, result)
        if self.disk_dir is not None:
            self._disk_put(key, result)

    def _mem_put(self, key: str, result: EngineResult) -> None:
        evicted = 0
        with self._lock:
            self._mem[key] = result
            self._mem.move_to_end(key)
            while len(self._mem) > self.max_entries:
                self._mem.popitem(last=False)
                self.evictions += 1
                evicted += 1
        for _ in range(evicted):
            self.obs.counter_add("cache.evictions")

    # -- disk tier -----------------------------------------------------------

    def _path_for(self, key: str) -> Path:
        return self.disk_dir / f"{_sha(key)}.json"

    def _disk_get(self, key: str) -> EngineResult | None:
        path = self._path_for(key)
        if not path.is_file():
            return None
        with self.obs.span("cache"):
            try:
                doc = json.loads(path.read_text())
                if doc.get("format_version") != CACHE_FORMAT_VERSION:
                    return None  # older layout: stale, not corrupt
                if doc.get("key") != key:
                    raise ValueError("stored key mismatch (hash collision?)")
                if doc.get("model_version") != self._model_version:
                    return None  # stale: model bumped under the same name
                result = result_from_doc(doc["result"])
                try:
                    # LRU recency lives in the mtime: under a quota, GC
                    # evicts oldest-mtime first, so a disk hit must
                    # refresh it — recency is USAGE, not write order
                    os.utime(path)
                except OSError:
                    pass  # read-only store: GC order degrades to FIFO
                return result
            except FileNotFoundError:
                # a peer's GC freed the record between the existence
                # check and the read (the documented concurrency
                # contract: deletes are whole-record, so a vanished
                # file is a plain miss, never damage)
                return None
            except (ValueError, KeyError, TypeError, OSError) as e:
                self.disk_errors += 1
                self.obs.counter_add("cache.disk_errors")
                # tpusim.guard: quarantine the bad record on FIRST
                # detection.  Before this, a corrupt record warned and
                # recomputed on every lookup that raced the healing put
                # (the driver's parallel pre-scan + the engine's own get
                # produced two warnings per run; a put that failed left
                # it warning forever).  Moving the file off the lookup
                # path makes the recompute heal it permanently: the next
                # get is a plain miss, and the recompute's put publishes
                # a fresh record.
                from tpusim.guard.store import quarantine_record

                if quarantine_record(path):
                    self.quarantined += 1
                warnings.warn(
                    f"tpusim.perf: corrupt result-cache entry {path} "
                    f"({type(e).__name__}: {e}); quarantined, recomputing",
                    RuntimeWarning,
                    stacklevel=2,
                )
                return None

    def _disk_put(self, key: str, result: EngineResult) -> None:
        if self._disk_write_disabled:
            return
        with self.obs.span("cache"):
            try:
                self.disk_dir.mkdir(parents=True, exist_ok=True)
                path = self._path_for(key)
                doc = {
                    "format_version": CACHE_FORMAT_VERSION,
                    "model_version": self._model_version,
                    "key": key,
                    "result": result_to_doc(result),
                }
                # pid AND thread ident: two daemon request threads
                # racing the same cold key must not share a tmp file
                # (one would publish the other's half-written record)
                tmp = path.with_suffix(
                    f".{os.getpid()}.{threading.get_ident()}.tmp"
                )
                _stage_write(tmp, json.dumps(doc), self.durable)
                governed = (
                    self.quota_bytes is not None
                    or self.quota_entries is not None
                )
                old_size = 0
                if governed:
                    # an overwrite replaces bytes, it doesn't add them:
                    # the estimate must take the DELTA or re-puts of hot
                    # keys cross the quota threshold early and trigger
                    # needless full-directory GC scans
                    try:
                        old_size = path.stat().st_size
                    except OSError:
                        old_size = 0
                os.replace(tmp, path)  # atomic: readers never see a torn file
                if self.durable:
                    # the rename itself must reach disk too, or a crash
                    # replays the old directory with the new inode gone
                    dir_fd = os.open(self.disk_dir, os.O_RDONLY)
                    try:
                        os.fsync(dir_fd)
                    finally:
                        os.close(dir_fd)
                if governed:
                    self._quota_gc(path, old_size)
            except OSError as e:
                self.disk_errors += 1
                self.obs.counter_add("cache.disk_errors")
                try:
                    tmp.unlink()
                except (OSError, NameError):
                    pass
                if fatal_write_disable(
                    e,
                    f"tpusim.perf: result-cache write failed under "
                    f"{self.disk_dir} ({e}); disabling further "
                    f"disk writes for this cache instance "
                    f"(reads and in-memory caching continue)",
                ):
                    self._disk_write_disabled = True
                    return
                warnings.warn(
                    f"tpusim.perf: result-cache write failed under "
                    f"{self.disk_dir} ({e}); continuing uncached",
                    RuntimeWarning,
                    stacklevel=2,
                )

    # -- tpusim.guard: quota GC + memory governance --------------------------

    def _quota_gc(self, new_path: Path, old_size: int = 0) -> None:
        """Post-publish quota enforcement: account the record just
        written (as a DELTA against ``old_size``, the bytes the same
        key held before an overwrite — 0 for a fresh record) and, when
        the store's estimated size crosses the quota, run the
        crash-safe LRU GC (:func:`tpusim.guard.store.gc_store` —
        whole-record deletes by mtime, idempotent under concurrent
        daemon + N forked workers).  The estimate refreshes to the
        authoritative scan on every GC, so drift from peers' puts is
        bounded by one quota excursion."""
        try:
            size = new_path.stat().st_size
        except OSError:
            size = 0
        with self._lock:
            if self._disk_bytes_est is None:
                # tier-inclusive seed: the quota governs the WHOLE
                # store dir (result + compiled records — guard's
                # RECORD_PATTERNS is the one tier definition), so the
                # estimate must start from everything GC would scan
                from tpusim.guard.store import _record_paths

                total = count = 0
                for p in _record_paths(self.disk_dir):
                    try:
                        total += p.stat().st_size
                        count += 1
                    except OSError:
                        pass
                self._disk_bytes_est = total
                self._disk_entries_est = count
            else:
                self._disk_bytes_est += size - old_size
                if old_size == 0:
                    self._disk_entries_est += 1
            over = (
                (self.quota_bytes is not None
                 and self._disk_bytes_est > self.quota_bytes)
                or (self.quota_entries is not None
                    and self._disk_entries_est > self.quota_entries)
            )
        if not over:
            return
        from tpusim.guard.store import gc_store

        res = gc_store(
            self.disk_dir, quota_bytes=self.quota_bytes,
            max_entries=self.quota_entries,
        )
        with self._lock:
            self.gc_runs += 1
            self.gc_deleted += res.deleted
            self.gc_freed_bytes += res.freed_bytes
            self._disk_bytes_est = res.remaining_bytes
            self._disk_entries_est = res.remaining_entries

    def shrink(self, factor: float = 0.5, floor: int = 16) -> int:
        """Halve (by default) the in-memory LRU's entry budget and trim
        to it — the memory watchdog's first ladder step.  Cached results
        re-materialize from the disk tier or a recompute; they are the
        definition of droppable state.  Returns the entries dropped."""
        dropped = 0
        with self._lock:
            self.max_entries = max(int(self.max_entries * factor), floor)
            while len(self._mem) > self.max_entries:
                self._mem.popitem(last=False)
                self.evictions += 1
                dropped += 1
            self.lru_shrinks += 1
        for _ in range(dropped):
            self.obs.counter_add("cache.evictions")
        return dropped

    def restore_entry_budget(self, max_entries: int) -> None:
        """Reverse :meth:`shrink` — the watchdog's recovery hook.  Only
        the budget comes back (entries refill on demand); without this,
        repeated transient excursions would ratchet a long-lived
        daemon's L1 down to the floor for the rest of its life."""
        with self._lock:
            self.max_entries = max(int(max_entries), 1)

    def guard_stats_dict(self) -> dict[str, float]:
        """Quota/GC accounting, stamped by the driver under the
        ``guard_`` prefix ONLY when a quota is set (the faults_*
        discipline: un-governed runs stay key-identical)."""
        with self._lock:
            return {
                "store_quota_bytes": self.quota_bytes or 0,
                "store_quota_entries": self.quota_entries or 0,
                "store_bytes_est": self._disk_bytes_est or 0,
                "store_entries_est": self._disk_entries_est,
                "store_gc_runs_total": self.gc_runs,
                "store_gc_deleted_total": self.gc_deleted,
                "store_gc_freed_bytes_total": self.gc_freed_bytes,
                "store_quarantined_total": self.quarantined,
                "lru_shrinks_total": self.lru_shrinks,
            }

    def flush(self) -> int:
        """Ensure every in-memory entry has its disk record (no-op for
        memory-only caches).  Normal operation writes through at ``put``
        time; this heals records whose write failed transiently (disk
        full, permission blip) — the serving daemon calls it on SIGTERM
        drain so a restart warms from everything the process computed.
        Returns the number of records written."""
        if self.disk_dir is None or self._disk_write_disabled:
            return 0
        with self._lock:
            items = list(self._mem.items())
        healed = 0
        for key, result in items:
            if self._disk_write_disabled:
                break
            if not self._path_for(key).is_file():
                self._disk_put(key, result)
                healed += 1
        return healed

    # -- reporting -----------------------------------------------------------

    def stats_dict(self) -> dict[str, float]:
        """Counter block the driver stamps under the ``cache_`` prefix
        (only when a cache is active — the faults_* discipline)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "disk_hits": self.disk_hits,
            "disk_errors": self.disk_errors,
            "entries": len(self._mem),
        }


def as_result_cache(spec, obs=None) -> ResultCache | None:
    """Coerce the ``--result-cache`` flag family to a cache instance:
    None/False → no cache; True → disk tier at :data:`DEFAULT_CACHE_DIR`;
    a path → disk tier there; an existing :class:`ResultCache` passes
    through (its obs hub is upgraded if it still has the no-op one)."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, ResultCache):
        if obs is not None and spec.obs is NULL_OBS:
            spec.obs = obs
        return spec
    if spec is True:
        return ResultCache(disk_dir=DEFAULT_CACHE_DIR, obs=obs)
    return ResultCache(disk_dir=spec, obs=obs)


# ---------------------------------------------------------------------------
# Compiled-module cache tier (tpusim.fastpath phase 1)
# ---------------------------------------------------------------------------

#: process-wide LRU of fastpath CompiledModule instances, keyed beside
#: the result cache: (module content fingerprint, capture platform,
#: composed-config fingerprint, model+parser version, lean flag).  The
#: platform joins the key for the same reason it joins result-cache
#: keys: the cost model's capture-backend dtype normalization makes
#: identical HLO text price differently per capture platform.  Scales
#: and topology are deliberately ABSENT — compiled columns hold healthy
#: per-op costs, and launch-class transforms apply at price time, which
#: is exactly why a fault sweep's every degraded class shares one
#: compile.
_COMPILED: OrderedDict = OrderedDict()
COMPILED_CACHE_MAX = 256
_compiled_hits = 0
_compiled_misses = 0
#: the serving daemon prices from many request threads against this one
#: process-wide tier; the lock covers the LRU mutations (move_to_end
#: racing an eviction corrupts an OrderedDict), not compilation itself —
#: two threads compiling the same cold key just duplicate pure work
_compiled_lock = threading.Lock()


def _compiled_key(module, config: SimConfig, lean: bool) -> tuple | None:
    mfp = module_fingerprint(module)
    if mfp is None:
        return None
    platform = str(module.meta.get("platform", "")) if module.meta else ""
    return (
        mfp, platform, config_fingerprint(config),
        f"{model_version()}+{parser_version()}", lean,
    )


def compiled_key_str(key: tuple) -> str:
    """The durable-tier string form of a compiled-module key (the same
    five components the in-memory tier keys on, in the same order)."""
    mfp, platform, cfg_fp, mv, lean = key
    return "|".join((
        mfp, f"p={platform}", cfg_fp, mv, "lean" if lean else "full",
    ))


def compiled_for(module, engine):
    """The fastpath's one compile per (module content, config): return
    a cached :class:`tpusim.fastpath.compile.CompiledModule` or mint
    one.  Engines with a caller-supplied cost model bypass the shared
    tier (their model is outside every fingerprint) and pin compiled
    columns to the module object + model token instead."""
    global _compiled_hits, _compiled_misses
    from tpusim.fastpath.compile import compile_module

    lean = bool(getattr(module, "stream_lean", False))
    if not getattr(engine, "_default_cost_model", True):
        token = getattr(engine.cost, "_cache_token", None)
        attr = getattr(module, "_fastpath_custom_cms", None)
        if attr is None:
            attr = {}
            try:
                module._fastpath_custom_cms = attr
            except (AttributeError, TypeError):
                return compile_module(
                    module, engine.cost, engine.config, lean=lean,
                    release_ir=lean,
                )
        key = (token, config_fingerprint(engine.config), lean)
        cm = attr.get(key)
        if cm is None:
            cm = attr[key] = compile_module(
                module, engine.cost, engine.config, lean=lean,
                release_ir=lean,
            )
        return cm

    key = _compiled_key(module, engine.config, lean)
    if key is None:
        # no stable fingerprint: fall back to a module-object attr so
        # repeated runs of the same object still compile once
        attr = getattr(module, "_fastpath_cm", None)
        ckey = (config_fingerprint(engine.config), lean)
        if isinstance(attr, dict) and ckey in attr:
            return attr[ckey]
        cm = compile_module(
            module, engine.cost, engine.config, lean=lean,
            release_ir=lean,
        )
        try:
            if not isinstance(attr, dict):
                attr = {}
                module._fastpath_cm = attr
            attr[ckey] = cm
        except (AttributeError, TypeError):
            pass
        return cm

    from tpusim.fastpath.store import get_compile_store

    store = get_compile_store()
    with _compiled_lock:
        cm = _COMPILED.get(key)
        if cm is not None:
            _COMPILED.move_to_end(key)
            _compiled_hits += 1
    if cm is not None:
        # the tier holds only a weak module ref; rebind the live object
        # (same content hash by key construction — the columns
        # transfer) so not-yet-reached computations can still compile
        cm.bind(module, engine.cost)
        if store is not None and cm._store_key is None:
            # a store activated after this instance was minted: adopt
            # it, so columns still publish at the next pricing walk
            cm._store_key = compiled_key_str(key)
        return cm
    if store is not None:
        # durable tier (tpusim.fastpath.store): mmap-load the columns
        # a peer process (or a previous life of this one) compiled —
        # BEFORE any lazy compile, which is what lets a warm store
        # price a lazily-loaded module with zero IR construction
        keystr = compiled_key_str(key)
        cm = store.load(keystr, module, engine)
        if cm is not None:
            cm._store_key = keystr
            with _compiled_lock:
                _COMPILED[key] = cm
                while len(_COMPILED) > COMPILED_CACHE_MAX:
                    _COMPILED.popitem(last=False)
            return cm
    cm = compile_module(
        module, engine.cost, engine.config, lean=lean, release_ir=lean,
    )
    if store is not None:
        cm._store_key = compiled_key_str(key)
    with _compiled_lock:
        _compiled_misses += 1
        _COMPILED[key] = cm
        while len(_COMPILED) > COMPILED_CACHE_MAX:
            _COMPILED.popitem(last=False)
    return cm


def clear_compiled_cache() -> int:
    """Drop the process-wide compiled-module tier (the memory
    watchdog's second ladder step).  Compiles are pure functions of
    content + config, rebuilt on demand — the only cost of clearing is
    the next pricing call's recompile.  Returns the entries dropped."""
    with _compiled_lock:
        n = len(_COMPILED)
        _COMPILED.clear()
    return n


def set_compiled_cache_max(max_entries: int) -> None:
    """Bound the compiled-module tier (the ``tpusim.guard`` quota for
    the in-memory compiled store); trims immediately when lowered."""
    global COMPILED_CACHE_MAX
    COMPILED_CACHE_MAX = max(int(max_entries), 1)
    with _compiled_lock:
        while len(_COMPILED) > COMPILED_CACHE_MAX:
            _COMPILED.popitem(last=False)


def compiled_cache_stats() -> dict[str, float]:
    """Counters for the ``fastpath_`` stats block (stamped by the
    driver only when a pricing backend was explicitly requested or a
    durable compile store is active).  The ``store_*`` keys ride only
    in the latter case — the faults_* discipline at sub-key grain."""
    out = {
        "compile_hits": _compiled_hits,
        "compile_misses": _compiled_misses,
        "compiled_modules": len(_COMPILED),
    }
    from tpusim.fastpath.store import get_compile_store

    store = get_compile_store()
    if store is not None:
        out.update(store.stats_dict())
        # the cold-path contract's observable: how many IR ops this
        # process has built (a warm store holds it at zero)
        from tpusim.ir import ir_build_counter

        out["ir_ops_built"] = ir_build_counter["ops"]
    return out


# ---------------------------------------------------------------------------
# Engine wiring
# ---------------------------------------------------------------------------


class CachedEngine(Engine):
    """An :class:`Engine` whose ``run`` consults a :class:`ResultCache`.

    The cache engages only for runs whose result is pure counters: obs
    cycle-window sampling and timeline recording both attach run-scoped
    objects, so those runs always price live.  A ``result_cache`` of
    None makes this an exact Engine (one branch per module run)."""

    def __init__(self, *args, result_cache: ResultCache | None = None, **kw):
        super().__init__(*args, **kw)
        self.result_cache = result_cache
        # a caller-supplied cost model is outside the cache key (which
        # fingerprints only the config + model sources), so such engines
        # must never share results with the default-model population —
        # bypass rather than silently cross-serve.  Engine's signature:
        # (config, topology, cost_model, ...) — 3rd positional.
        self._cache_eligible = (
            kw.get("cost_model") is None and len(args) < 3
        )

    def run(self, module) -> EngineResult:
        cache = self.result_cache
        if (
            cache is None
            or not self._cache_eligible
            or self.record_timeline
            or (self.obs.enabled and self.obs.sample)
        ):
            return super().run(module)
        key = cache.key_for(
            module, self.config,
            (self.clock_scale, self.hbm_scale),
            self.topology,
        )
        if key is None:
            return super().run(module)
        cached = cache.get(key)
        if cached is not None:
            return cached
        result = super().run(module)
        cache.put(key, result)
        return result
