"""Deterministic worker pool — the fan-out half of :mod:`tpusim.perf`.

The reference parallelizes at the job level (``run_simulations.py``
submits one process per benchmark×config cell); inside one simulation it
is single-threaded.  tpusim's fan-out layers (link sweeps, correlation
regen, the driver's per-segment module pricing) are embarrassingly
parallel *and* pure — each task is a closed-form float computation — so
a process pool with an **ordered** result merge reproduces the serial
path bit-for-bit: same tasks, same math, same merge order.

Contract:

* ``workers<=1`` (the default when ``$TPUSIM_WORKERS`` is unset)
  short-circuits to a plain in-process loop — no pool, no pickling, no
  behavior change;
* the start method is ``fork`` where available (context transfers by
  inheritance — no pickling of pods/configs) with a ``spawn`` fallback
  (context travels through the initializer, so it must pickle);
* results always merge in task-submission order (``Pool.map``
  semantics), so downstream reports cannot depend on scheduling;
* any pool-infrastructure failure falls back to the serial loop rather
  than failing the run — parallelism is an optimization, never a
  requirement.

Worker functions must be module-level (pickled by qualified name) and
reach their shared inputs through :func:`pool_context`, set per call via
``map_ordered(..., context=...)``.
"""

from __future__ import annotations

import multiprocessing
import os
import pickle
import signal
import threading
from typing import Any, Callable, Iterable

__all__ = [
    "DeferSignals",
    "env_workers",
    "map_ordered",
    "pool_context",
    "resolve_workers",
]


class _DeferSignals:
    """Defer SIGTERM/SIGINT while a pooled map is in flight.

    The default SIGTERM disposition kills the parent instantly —
    skipping atexit, so the pool's daemonic children are ORPHANED
    mid-task (they finish their item, then block forever on the dead
    task queue).  While this guard is active the signal is only
    recorded; on exit — after the pool context has reaped its workers —
    the original disposition is restored and the signal re-delivered,
    so the process still honors the kill, just *after* the in-flight
    work has drained (and, for cached sweeps, landed in the disk tier).

    Signal handlers can only be installed from the main thread; from
    worker threads (the serving daemon's request threads) this is a
    no-op and the process-level handlers keep their behavior."""

    _SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __enter__(self) -> "_DeferSignals":
        self._received: list[int] = []
        self._prev: dict[int, object] = {}
        self._active = (
            threading.current_thread() is threading.main_thread()
        )
        if self._active:
            try:
                for s in self._SIGNALS:
                    self._prev[s] = signal.signal(
                        s, lambda signum, frame: self._received.append(signum)
                    )
            except (ValueError, OSError):  # pragma: no cover - exotic hosts
                self._active = False
        return self

    def __exit__(self, *exc) -> bool:
        if self._active:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            for signum in self._received:
                os.kill(os.getpid(), signum)
        return False


#: public name: the serve v2 supervisor wraps its initial worker fork in
#: the same discipline (a SIGTERM landing mid-fork defers until the
#: fleet is registered, so the drain path reaps children, never orphans)
DeferSignals = _DeferSignals

#: shared per-call inputs for worker functions; in the parent this is set
#: by :func:`map_ordered` (the serial path uses it too, so workers are
#: path-agnostic), in children by the pool initializer.
_POOL_CONTEXT: Any = None


def _init_worker(context: Any) -> None:
    global _POOL_CONTEXT
    _POOL_CONTEXT = context


def pool_context() -> Any:
    """The ``context=`` object of the in-flight :func:`map_ordered` call."""
    return _POOL_CONTEXT


def env_workers() -> int | None:
    """``$TPUSIM_WORKERS`` as an int, or None when unset/garbage."""
    raw = os.environ.get("TPUSIM_WORKERS", "").strip()
    if not raw:
        return None
    try:
        return max(int(raw), 1)
    except ValueError:
        return None


def resolve_workers(workers: int | None) -> int:
    """Effective worker count: the explicit request, else
    ``$TPUSIM_WORKERS``, else 1 (serial — parallelism is opt-in).
    Inside a pool worker this is always 1: daemonic processes cannot
    fork children, so nested fan-out degrades to the serial path."""
    if multiprocessing.current_process().daemon:
        return 1
    if workers is not None:
        return max(int(workers), 1)
    return env_workers() or 1


def _serial(fn: Callable, items: list, context: Any) -> list:
    # save/restore rather than reset: a nested serial map (e.g. a sweep
    # worker whose driver falls back to serial) must not clobber the
    # outer call's context for its remaining items
    prev = _POOL_CONTEXT
    _init_worker(context)
    try:
        return [fn(item) for item in items]
    finally:
        _init_worker(prev)


def map_ordered(
    fn: Callable,
    items: Iterable,
    workers: int | None = None,
    context: Any = None,
    chunksize: int = 1,
) -> list:
    """``[fn(item) for item in items]``, fanned over ``workers``
    processes, results in input order.

    ``fn`` must be a module-level function when ``workers > 1``;
    ``context`` is exposed to it via :func:`pool_context` on every path
    (serial included), so workers never branch on how they were run."""
    items = list(items)
    w = min(resolve_workers(workers), len(items))
    if w <= 1:
        return _serial(fn, items, context)
    try:
        # dispatchability probe: workers import fn by qualified name, so
        # a closure/local fn can never run in a pool — take the serial
        # path up front instead of interpreting a later AttributeError
        # (which a TASK may legitimately raise) as dispatch failure
        pickle.dumps(fn)
    except Exception:
        return _serial(fn, items, context)
    methods = multiprocessing.get_all_start_methods()
    ctx = multiprocessing.get_context(
        "fork" if "fork" in methods else "spawn"
    )
    try:
        pool = ctx.Pool(w, initializer=_init_worker, initargs=(context,))
    except (OSError, ValueError, ImportError,
            multiprocessing.ProcessError, pickle.PicklingError):
        # pool INFRASTRUCTURE failed (fd limits, sandboxed fork,
        # unpicklable context on spawn): degrade to the serial loop —
        # same tasks, same order, same results
        return _serial(fn, items, context)
    try:
        # SIGTERM/SIGINT during the map drain the in-flight tasks and
        # reap the children before the signal takes effect (see
        # _DeferSignals) — a killed sweep leaves no orphan workers
        with _DeferSignals(), pool:
            return pool.map(fn, items, chunksize=chunksize)
    except pickle.PicklingError:
        # items failed to pickle — a dispatch problem (fn was probed
        # above), not a task failure, so the serial loop still applies.
        # Real task exceptions (OSError from a missing trace,
        # AttributeError from a malformed op) propagate unchanged.
        return _serial(fn, items, context)
