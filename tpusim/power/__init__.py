"""TPUWattch — the AccelWattch rebuild for TPU units.

The reference's power layer (``src/accelwattch/``, a McPAT/CACTI fork) maps
per-pipeline activity counters to per-component dynamic power plus static
power, sampled from ``gpgpu_sim::cycle()`` (``gpu-sim.cc:1993-2001``) with
an opcode→component table (``ISA_Def/accelwattch_component_mapping.h``).

Ours maps the timing engine's counters — MXU flops, VPU ops,
transcendentals, HBM/vmem/ICI bytes, unit busy cycles — through per-unit
energy coefficients (pJ/op, pJ/byte) re-fit to TPU generations, plus
leakage and idle components.  Counters were plumbed from day 1
(SURVEY.md §7 step 9): :class:`tpusim.timing.engine.EngineResult` is the
``power_stat.h`` equivalent.
"""

from tpusim.power.model import PowerCoefficients, PowerModel, PowerReport

__all__ = ["PowerCoefficients", "PowerModel", "PowerReport"]
