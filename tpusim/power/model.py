"""Per-unit energy/power model.

Energy coefficients are first-principles estimates for a ~5nm-class TPU,
chosen so the derived chip power at full utilization lands near published
TDPs (v5e ~ 200W class, v5p ~ 500W class); the fitting harness
(:mod:`tpusim.harness.tuner`) can refine them when real power telemetry is
available — the analogue of AccelWattch's quadprog coefficient fit
(``util/accelwattch/quadprog_solver.m``, ``AccelWattch.md:110-125``).

Model: for one simulated execution,

    E_dyn  = mxu_pj * mxu_flops + vpu_pj * vpu_ops + sfu_pj * transcendentals
           + hbm_pj * hbm_bytes + vmem_pj * vmem_bytes + ici_pj * ici_bytes
    P_avg  = E_dyn / t + P_static + P_idle_clock

mirroring AccelWattch's dynamic-activity × per-access-energy + leakage
split (``gpgpu_sim_wrapper.cc``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpusim.timing.engine import EngineResult

__all__ = ["PowerCoefficients", "PowerModel", "PowerReport"]


@dataclass(frozen=True)
class PowerCoefficients:
    """pJ per event, plus static watts — one set per TPU generation."""

    name: str = "v5p"
    mxu_pj_per_flop: float = 0.6       # bf16 MAC energy amortized
    vpu_pj_per_flop: float = 1.2
    sfu_pj_per_op: float = 4.0         # transcendentals
    hbm_pj_per_byte: float = 6.0       # HBM2e/3-class access energy
    vmem_pj_per_byte: float = 0.8      # on-chip SRAM
    ici_pj_per_byte: float = 10.0      # SerDes + link
    static_watts: float = 70.0         # leakage
    idle_clock_watts: float = 35.0     # clock tree / sequencer


#: per-generation coefficient presets (fit targets: published TDP class)
POWER_PRESETS: dict[str, PowerCoefficients] = {
    "v4": PowerCoefficients(name="v4", mxu_pj_per_flop=0.35,
                            static_watts=55.0),
    "v5e": PowerCoefficients(name="v5e", mxu_pj_per_flop=0.30,
                             static_watts=40.0, idle_clock_watts=20.0),
    "v5p": PowerCoefficients(name="v5p"),
    "v6e": PowerCoefficients(name="v6e", mxu_pj_per_flop=0.18,
                             static_watts=45.0),
}


@dataclass
class PowerReport:
    """Per-component energy breakdown for one simulated execution — the
    ``accelwattch_power_report.log`` equivalent."""

    seconds: float
    component_joules: dict[str, float] = field(default_factory=dict)
    static_watts: float = 0.0
    idle_watts: float = 0.0

    @property
    def dynamic_joules(self) -> float:
        return sum(self.component_joules.values())

    @property
    def total_joules(self) -> float:
        return (
            self.dynamic_joules
            + (self.static_watts + self.idle_watts) * self.seconds
        )

    @property
    def avg_watts(self) -> float:
        if self.seconds <= 0:
            return 0.0
        return self.total_joules / self.seconds

    def stats_dict(self) -> dict[str, float]:
        d = {
            "power_avg_watts": self.avg_watts,
            "energy_total_j": self.total_joules,
            "energy_dynamic_j": self.dynamic_joules,
            "power_static_watts": self.static_watts + self.idle_watts,
        }
        for comp, j in self.component_joules.items():
            d[f"energy_{comp}_j"] = j
        return d

    def report_text(self) -> str:
        lines = ["TPUWattch power report", "-" * 40]
        lines.append(f"elapsed            = {self.seconds:.6g} s")
        for comp, j in sorted(self.component_joules.items()):
            w = j / self.seconds if self.seconds else 0.0
            lines.append(f"{comp:18s} = {j:.6g} J ({w:.3g} W)")
        lines.append(f"{'static+idle':18s} = "
                     f"{(self.static_watts + self.idle_watts) * self.seconds:.6g} J "
                     f"({self.static_watts + self.idle_watts:.3g} W)")
        lines.append(f"{'avg power':18s} = {self.avg_watts:.6g} W")
        return "\n".join(lines)


class PowerModel:
    def __init__(self, coeffs: PowerCoefficients | str = "v5p"):
        if isinstance(coeffs, str):
            coeffs = POWER_PRESETS.get(coeffs, PowerCoefficients(name=coeffs))
        self.coeffs = coeffs

    def report(self, result: EngineResult) -> PowerReport:
        c = self.coeffs
        pj = {
            "mxu": c.mxu_pj_per_flop * result.mxu_flops,
            "vpu": c.vpu_pj_per_flop * max(
                result.flops - result.mxu_flops - result.transcendentals, 0.0
            ),
            "sfu": c.sfu_pj_per_op * result.transcendentals,
            "hbm": c.hbm_pj_per_byte * result.hbm_bytes,
            "vmem": c.vmem_pj_per_byte * result.vmem_bytes,
            "ici": c.ici_pj_per_byte * result.ici_bytes,
        }
        return PowerReport(
            seconds=max(result.seconds, 1e-12),
            component_joules={k: v * 1e-12 for k, v in pj.items()},
            static_watts=c.static_watts,
            idle_watts=c.idle_clock_watts,
        )
