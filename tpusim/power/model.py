"""Per-unit energy/power model.

Energy coefficients are first-principles estimates for a ~5nm-class TPU,
chosen so the derived chip power at full utilization lands near published
TDPs (v5e ~ 200W class, v5p ~ 500W class); the fitting harness
(:mod:`tpusim.harness.tuner`) can refine them when real power telemetry is
available — the analogue of AccelWattch's quadprog coefficient fit
(``util/accelwattch/quadprog_solver.m``, ``AccelWattch.md:110-125``).

Model: for one simulated execution,

    E_dyn  = mxu_pj * mxu_flops + vpu_pj * vpu_ops + sfu_pj * transcendentals
           + hbm_pj * hbm_bytes + vmem_pj * vmem_bytes + ici_pj * ici_bytes
    P_avg  = E_dyn / t + P_static + P_idle_clock

mirroring AccelWattch's dynamic-activity × per-access-energy + leakage
split (``gpgpu_sim_wrapper.cc``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpusim.timing.engine import EngineResult

__all__ = [
    "PowerCoefficients", "PowerModel", "PowerReport", "power_timeline",
    "dvfs_overlays", "POWER_PRESETS",
]


@dataclass(frozen=True)
class PowerCoefficients:
    """pJ per event, plus static watts — one set per TPU generation."""

    name: str = "v5p"
    mxu_pj_per_flop: float = 0.6       # bf16 MAC energy amortized
    vpu_pj_per_flop: float = 1.2
    sfu_pj_per_op: float = 4.0         # transcendentals
    hbm_pj_per_byte: float = 6.0       # HBM2e/3-class access energy
    vmem_pj_per_byte: float = 0.8      # on-chip SRAM
    ici_pj_per_byte: float = 10.0      # SerDes + link
    static_watts: float = 70.0         # leakage
    idle_clock_watts: float = 35.0     # clock tree / sequencer

    def component_picojoules(
        self,
        *,
        mxu_flops: float = 0.0,
        flops: float = 0.0,
        transcendentals: float = 0.0,
        hbm_bytes: float = 0.0,
        vmem_bytes: float = 0.0,
        ici_bytes: float = 0.0,
    ) -> dict[str, float]:
        """Per-component dynamic energy (pJ) for one set of activity
        counts — THE energy accounting, shared by the end-of-run
        :meth:`PowerModel.report` and the obs layer's per-window watts
        track so the two can't diverge.  VPU flops are the non-MXU,
        non-transcendental remainder."""
        return {
            "mxu": self.mxu_pj_per_flop * mxu_flops,
            "vpu": self.vpu_pj_per_flop * max(
                flops - mxu_flops - transcendentals, 0.0
            ),
            "sfu": self.sfu_pj_per_op * transcendentals,
            "hbm": self.hbm_pj_per_byte * hbm_bytes,
            "vmem": self.vmem_pj_per_byte * vmem_bytes,
            "ici": self.ici_pj_per_byte * ici_bytes,
        }

    def scaled(self, voltage_scale: float) -> "PowerCoefficients":
        """DVFS voltage scaling (the AccelWattch DVFS slot): per-event
        switching energy goes as V², and leakage roughly tracks V² at
        nearby operating points.  Pair with a ``clock_ghz`` overlay on the
        timing side — :func:`dvfs_overlays` builds both."""
        v2 = voltage_scale ** 2
        return PowerCoefficients(
            name=self.name,
            mxu_pj_per_flop=self.mxu_pj_per_flop * v2,
            vpu_pj_per_flop=self.vpu_pj_per_flop * v2,
            sfu_pj_per_op=self.sfu_pj_per_op * v2,
            hbm_pj_per_byte=self.hbm_pj_per_byte,   # HBM rail is separate
            vmem_pj_per_byte=self.vmem_pj_per_byte * v2,
            ici_pj_per_byte=self.ici_pj_per_byte,   # SerDes rail too
            static_watts=self.static_watts * v2,
            idle_clock_watts=self.idle_clock_watts * v2 * voltage_scale,
        )


def dvfs_overlays(base_clock_ghz: float, freq_scale: float) -> list[dict]:
    """Config overlays for a DVFS operating point: scale the core clock
    (timing side) and record the scale for the power side (``dvfs_scale``
    is read by the driver when building the PowerModel).  Voltage is
    assumed ∝ frequency near the nominal point."""
    return [{
        "arch": {"clock_ghz": base_clock_ghz * freq_scale},
        "dvfs_scale": freq_scale,
    }]


#: per-generation coefficient presets (fit targets: published TDP class)
POWER_PRESETS: dict[str, PowerCoefficients] = {
    "v4": PowerCoefficients(name="v4", mxu_pj_per_flop=0.35,
                            static_watts=55.0),
    "v5e": PowerCoefficients(name="v5e", mxu_pj_per_flop=0.30,
                             static_watts=40.0, idle_clock_watts=20.0),
    "v5p": PowerCoefficients(name="v5p"),
    "v6e": PowerCoefficients(name="v6e", mxu_pj_per_flop=0.18,
                             static_watts=45.0),
}


@dataclass
class PowerReport:
    """Per-component energy breakdown for one simulated execution — the
    ``accelwattch_power_report.log`` equivalent."""

    seconds: float
    component_joules: dict[str, float] = field(default_factory=dict)
    static_watts: float = 0.0
    idle_watts: float = 0.0

    @property
    def dynamic_joules(self) -> float:
        return sum(self.component_joules.values())

    @property
    def total_joules(self) -> float:
        return (
            self.dynamic_joules
            + (self.static_watts + self.idle_watts) * self.seconds
        )

    @property
    def avg_watts(self) -> float:
        if self.seconds <= 0:
            return 0.0
        return self.total_joules / self.seconds

    def stats_dict(self) -> dict[str, float]:
        d = {
            "power_avg_watts": self.avg_watts,
            "energy_total_j": self.total_joules,
            "energy_dynamic_j": self.dynamic_joules,
            "power_static_watts": self.static_watts + self.idle_watts,
        }
        for comp, j in self.component_joules.items():
            d[f"energy_{comp}_j"] = j
        return d

    def report_text(self) -> str:
        lines = ["TPUWattch power report", "-" * 40]
        lines.append(f"elapsed            = {self.seconds:.6g} s")
        for comp, j in sorted(self.component_joules.items()):
            w = j / self.seconds if self.seconds else 0.0
            lines.append(f"{comp:18s} = {j:.6g} J ({w:.3g} W)")
        lines.append(f"{'static+idle':18s} = "
                     f"{(self.static_watts + self.idle_watts) * self.seconds:.6g} J "
                     f"({self.static_watts + self.idle_watts:.3g} W)")
        lines.append(f"{'avg power':18s} = {self.avg_watts:.6g} W")
        return "\n".join(lines)


class PowerModel:
    def __init__(
        self,
        coeffs: PowerCoefficients | str = "v5p",
        dvfs_scale: float = 1.0,
    ):
        if isinstance(coeffs, str):
            # fitted coefficients (committed by the power-validation fit,
            # tpusim/power/fitted/<name>.json) take precedence over the
            # first-principles presets
            from tpusim.power.telemetry import load_fitted

            coeffs = load_fitted(coeffs) or POWER_PRESETS.get(
                coeffs, PowerCoefficients(name=coeffs)
            )
        if dvfs_scale != 1.0:
            coeffs = coeffs.scaled(dvfs_scale)
        self.coeffs = coeffs

    def report(
        self, result: EngineResult, measured_seconds: float | None = None,
    ) -> PowerReport:
        """Power report from one execution's activity counts.

        ``measured_seconds`` is the AccelWattch **HW-mode** slot
        (``AccelWattch.md``: activity factors with real kernel
        durations): the event counts are exact static properties of the
        program, so substituting the measured device time for the
        simulated time yields a power estimate independent of the timing
        model's error — the form the hw-validation CSV pipeline compares
        against NVML watts."""
        c = self.coeffs
        pj = c.component_picojoules(
            mxu_flops=result.mxu_flops,
            flops=result.flops,
            transcendentals=result.transcendentals,
            hbm_bytes=result.hbm_bytes,
            vmem_bytes=result.vmem_bytes,
            ici_bytes=result.ici_bytes,
        )
        seconds = (
            measured_seconds if measured_seconds is not None
            else result.seconds
        )
        return PowerReport(
            seconds=max(seconds, 1e-12),
            component_joules={k: v * 1e-12 for k, v in pj.items()},
            static_watts=c.static_watts,
            idle_watts=c.idle_clock_watts,
        )


def power_timeline(samples, arch, coeffs: PowerCoefficients | str = "v5p",
                   dvfs_scale: float = 1.0):
    """Per-window power from interval utilization samples — the
    time-resolved view AccelWattch produces by calling ``mcpat_cycle``
    every sample period (``gpu-sim.cc:1993-2001``).

    Per-unit dynamic power is the unit's peak event rate × per-event
    energy × its busy fraction in the window (a roofline-style activity
    factor; the totals-based :meth:`PowerModel.report` remains the
    energy-accurate accounting).  Returns one dict per window.
    """
    if isinstance(coeffs, str):
        coeffs = POWER_PRESETS.get(coeffs, PowerCoefficients(name=coeffs))
    if dvfs_scale != 1.0:
        coeffs = coeffs.scaled(dvfs_scale)
    c = coeffs
    # peak dynamic watts per unit at 100% utilization; the DMA rate
    # mirrors what the engine actually models (efficiency-derated HBM),
    # and the ICI link count follows the configured topology
    ici_axes = {"torus3d": 3, "torus2d": 2, "mesh2d": 2, "ring": 1}.get(
        arch.ici.topology, 3
    )
    peak = {
        "mxu": c.mxu_pj_per_flop * arch.peak_bf16_flops * 1e-12,
        "vpu": c.vpu_pj_per_flop * arch.vpu_flops_per_cycle
               * arch.clock_hz * 1e-12,
        "dma": c.hbm_pj_per_byte * arch.hbm_bandwidth
               * arch.hbm_efficiency * 1e-12,
        "ici": c.ici_pj_per_byte * arch.ici.link_bandwidth
               * max(arch.ici.links_per_axis, 1) * 2 * ici_axes * 1e-12,
    }
    out = []
    for s in samples:
        comps = {
            unit: peak.get(unit, 0.0) * s.utilization(unit)
            for unit in s.unit_busy
            if peak.get(unit)
        }
        total = sum(comps.values()) + c.static_watts + c.idle_clock_watts
        out.append({
            "t0": s.t0,
            "t1": s.t1,
            "watts": total,
            "components": comps,
            "static_watts": c.static_watts + c.idle_clock_watts,
        })
    return out
