"""Power telemetry capture + coefficient fitting.

The rebuild of AccelWattch's hardware-validation pipeline
(``util/accelwattch/accelwattch_hw_profiler/measureGpuPower.cpp`` — an
NVML sampler — plus ``quadprog_solver.m`` fitting per-component
coefficients to measured kernel power, ``AccelWattch.md:110-125``).

TPU equivalents:

* **telemetry hook** (:func:`read_power_watts`): tries the power sources a
  TPU-VM can expose — the ``tpu-info``/``libtpu`` metrics service and sysfs
  hwmon rails.  Returns ``None`` when none is available (tunneled
  single-chip images like this one expose neither), in which case the
  fitter falls back to anchor fixtures.
* **anchor fixtures** (:data:`POWER_ANCHORS`): published TDP-class
  operating points per generation (idle, dense-matmul full load, HBM-bound
  stream).  These are documented estimates standing in for silicon
  telemetry — the same role AccelWattch's ``hw_power_validation_volta.csv``
  plays, at much coarser grain; swap in measured samples when a telemetry
  source exists.
* **least-squares fit** (:func:`fit_power_coefficients`): solves
  ``watts ≈ Σ coeff_i · rate_i · 1e-12 + static`` over the samples with
  non-negativity clamping — the quadprog slot.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from tpusim.power.model import PowerCoefficients, POWER_PRESETS

__all__ = [
    "PowerSample",
    "read_power_watts",
    "probe_power_sources",
    "sample_workload_power",
    "anchor_samples",
    "fit_power_coefficients",
    "save_fitted",
    "load_fitted",
    "FITTED_DIR",
]

FITTED_DIR = Path(__file__).resolve().parent / "fitted"

#: activity-rate keys, in design-matrix order (events per second)
RATE_KEYS = (
    "mxu_flops", "vpu_flops", "transcendentals",
    "hbm_bytes", "vmem_bytes", "ici_bytes",
)

_COEF_FIELDS = (
    "mxu_pj_per_flop", "vpu_pj_per_flop", "sfu_pj_per_op",
    "hbm_pj_per_byte", "vmem_pj_per_byte", "ici_pj_per_byte",
)


@dataclass
class PowerSample:
    """One measured (or anchored) operating point."""

    name: str
    watts: float
    #: event rates per second, keyed by RATE_KEYS (missing = 0)
    rates: dict[str, float] = field(default_factory=dict)

    def row(self) -> list[float]:
        return [self.rates.get(k, 0.0) * 1e-12 for k in RATE_KEYS] + [1.0]


# ---------------------------------------------------------------------------
# telemetry hook
# ---------------------------------------------------------------------------


def read_power_watts() -> float | None:
    """Instantaneous chip power, or None when no source is available.

    Sources tried, in order (the measureGpuPower.cpp slot):
    1. the ``tpu_info`` library (TPU-VM metrics service, when installed);
    2. sysfs hwmon power rails (``/sys/class/hwmon/*/power*_input``, µW).

    One implementation with :func:`probe_power_sources` — the probe IS
    the source walk, this is just its scalar view."""
    return probe_power_sources()["watts"]


def probe_power_sources() -> dict:
    """Diagnose every power-telemetry source and report what happened —
    the committed evidence for why fitted coefficients are (or are not)
    anchor-based (VERDICT r3 #6: 'attempt the measurement; if the TPU-VM
    exposes no power counters, record that fact')."""
    import glob

    tried: list[dict] = []
    watts: float | None = None
    n_chips: int | None = None

    try:
        from tpu_info import metrics  # type: ignore

        chips = list(metrics.get_chip_usage())
        vals = [
            getattr(c, "power_usage_watts", None) for c in chips
        ]
        vals = [v for v in vals if v]
        if vals:
            watts = float(sum(vals))
            n_chips = len(vals)
            tried.append({"source": "tpu_info", "ok": True,
                          "watts": watts, "chips": n_chips})
        else:
            tried.append({"source": "tpu_info", "ok": False,
                          "detail": f"{len(chips)} chips, "
                                    "no power_usage_watts"})
    except ImportError as e:
        tried.append({"source": "tpu_info", "ok": False,
                      "detail": f"not installed: {e}"})
    except Exception as e:
        tried.append({"source": "tpu_info", "ok": False,
                      "detail": f"{type(e).__name__}: {e}"})

    rails = glob.glob("/sys/class/hwmon/hwmon*/power*_input")
    if rails:
        vals = []
        for p in rails:
            try:
                vals.append(int(Path(p).read_text().strip()))
            except (OSError, ValueError):
                continue
        vals = [v for v in vals if v > 0]  # idle rails report 0µW — not data
        if vals:
            if watts is None:
                watts = sum(vals) / 1e6
            tried.append({"source": "hwmon", "ok": True,
                          "rails": len(vals),
                          "watts": sum(vals) / 1e6})
        else:
            tried.append({"source": "hwmon", "ok": False,
                          "detail": f"{len(rails)} rails, none with a "
                                    "nonzero reading"})
    else:
        tried.append({"source": "hwmon", "ok": False,
                      "detail": "no /sys/class/hwmon power rails"})

    return {"watts": watts, "chips": n_chips, "tried": tried}


def sample_workload_power(
    fn, args, *, name: str = "workload", seconds: float = 3.0,
    poll_s: float = 0.1,
) -> PowerSample | None:
    """Run ``fn`` in a loop for ~``seconds`` while polling telemetry;
    returns the averaged sample (rates must be attached by the caller from
    the capture's cost analysis), or None without a telemetry source."""
    import time

    import jax

    if read_power_watts() is None:
        return None
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    out = jitted(*args)
    jax.block_until_ready(out)
    readings: list[float] = []
    t_end = time.time() + seconds
    while time.time() < t_end:
        out = jitted(*args)
        jax.block_until_ready(out)
        w = read_power_watts()
        if w is not None:
            readings.append(w)
        time.sleep(poll_s)
    if not readings:
        return None
    return PowerSample(name=name, watts=sum(readings) / len(readings))


# ---------------------------------------------------------------------------
# anchor fixtures (documented estimates — the TDP-class operating points)
# ---------------------------------------------------------------------------

#: per-arch anchors as (name, watts, utilization profile).  Utilizations
#: are fractions of the arch's peak rates; watts are published TDP-class
#: figures for the generation (chip max power: v4 ~192W per the TPUv4
#: ISCA'23 paper; v5e ~200W class; v5p ~500W class per public TDP
#: statements) interpolated to the operating point.  ESTIMATES, not
#: silicon measurements — replace via telemetry when available.
POWER_ANCHOR_POINTS: dict[str, list[tuple[str, float, dict[str, float]]]] = {
    "v5e": [
        ("idle", 60.0, {}),
        ("dense_matmul", 200.0,
         {"mxu_flops": 0.65, "hbm_bytes": 0.30, "vmem_bytes": 0.60}),
        # HBM-bound streaming sits far below TDP: ~2.3TB/s at HBM-class
        # ~6pJ/B is only ~15W of dynamic draw over idle
        ("hbm_stream", 85.0,
         {"vpu_flops": 0.20, "hbm_bytes": 0.85}),
        ("mixed_train", 180.0,
         {"mxu_flops": 0.45, "vpu_flops": 0.30, "hbm_bytes": 0.55,
          "vmem_bytes": 0.40, "transcendentals": 0.20}),
    ],
    "v5p": [
        ("idle", 105.0, {}),
        ("dense_matmul", 500.0,
         {"mxu_flops": 0.65, "hbm_bytes": 0.30, "vmem_bytes": 0.60}),
        ("hbm_stream", 135.0,
         {"vpu_flops": 0.20, "hbm_bytes": 0.85}),
        ("mixed_train", 440.0,
         {"mxu_flops": 0.45, "vpu_flops": 0.30, "hbm_bytes": 0.55,
          "vmem_bytes": 0.40, "transcendentals": 0.20}),
    ],
}


def _peak_rates(arch) -> dict[str, float]:
    """Peak event rates per second for an ArchConfig."""
    return {
        "mxu_flops": arch.peak_bf16_flops,
        "vpu_flops": arch.vpu_flops_per_cycle * arch.clock_hz,
        "transcendentals": arch.vpu_transcendental_per_cycle * arch.clock_hz,
        "hbm_bytes": arch.hbm_bandwidth,
        "vmem_bytes": arch.vmem_bandwidth_mult * arch.hbm_bandwidth,
        "ici_bytes": arch.ici.link_bandwidth * 6,
    }


def anchor_samples(arch_name: str) -> list[PowerSample]:
    """The fixture samples for one generation, utilizations resolved
    against the arch's peak rates."""
    from tpusim.timing.arch import arch_preset

    arch = arch_preset(arch_name)
    peaks = _peak_rates(arch)
    points = POWER_ANCHOR_POINTS.get(arch_name)
    if points is None:
        raise KeyError(
            f"no power anchors for {arch_name!r}; have "
            f"{sorted(POWER_ANCHOR_POINTS)}"
        )
    return [
        PowerSample(
            name=nm, watts=w,
            rates={k: u * peaks[k] for k, u in util.items()},
        )
        for nm, w, util in points
    ]


# ---------------------------------------------------------------------------
# fitting (the quadprog_solver.m slot)
# ---------------------------------------------------------------------------


def fit_power_coefficients(
    samples: list[PowerSample],
    name: str,
    *,
    prior_weight: float = 0.05,
) -> PowerCoefficients:
    """Least-squares fit of per-event energies + static watts to the
    samples — the quadprog slot.

    Anchor sets are few-sample and the design matrix is rank-deficient
    (7 unknowns, ~4 operating points), so an unconstrained solve attributes
    energy unphysically (e.g. all of a matmul's power billed to HBM).  The
    fit therefore regularizes toward the first-principles preset in
    *scaled* space: solve for per-coefficient scale factors s with a ridge
    pulling s→1, then clamp negatives.  prior_weight trades anchor
    exactness against physical attribution."""
    import numpy as np

    if len(samples) < 2:
        raise ValueError("need >= 2 samples to fit power coefficients")
    base = POWER_PRESETS.get(name, PowerCoefficients(name=name))
    prior = np.maximum(np.array(
        [getattr(base, f) for f in _COEF_FIELDS], dtype=np.float64,
    ), 1e-9)

    # stage 1: static power is directly observed by zero-activity samples
    # (the idle point); estimate it there rather than entangling it with
    # the under-determined dynamic fit
    idle = [s for s in samples if not any(s.rates.values())]
    loaded = [s for s in samples if any(s.rates.values())]
    if idle:
        static = float(sum(s.watts for s in idle) / len(idle))
    else:
        static = base.static_watts + base.idle_clock_watts
        loaded = samples

    # stage 2: dynamic coefficients on the static-subtracted residuals,
    # in prior-scaled space with a ridge pulling each scale toward 1
    # (rank-deficient anchor sets would otherwise attribute energy
    # unphysically — all of a matmul's power billed to HBM)
    A = np.array(
        [[s.rates.get(k, 0.0) * 1e-12 for k in RATE_KEYS] for s in loaded],
        dtype=np.float64,
    )
    b = np.array([s.watts - static for s in loaded], dtype=np.float64)
    Ap = A * prior[None, :]
    lam = prior_weight * float((Ap ** 2).sum()) / max(Ap.shape[1], 1)
    AtA = Ap.T @ Ap + lam * np.eye(Ap.shape[1])
    rhs = Ap.T @ b + lam * np.ones(Ap.shape[1])
    s = np.linalg.solve(AtA, rhs)
    x = np.maximum(s, 0.0) * prior
    kw = dict(zip(_COEF_FIELDS, (float(v) for v in x)))
    # split the fitted static between leakage and clock tree in the same
    # proportion as the preset (the fit cannot separate them)
    tot = base.static_watts + base.idle_clock_watts
    frac = base.static_watts / tot if tot > 0 else 0.5
    return PowerCoefficients(
        name=name,
        static_watts=static * frac,
        idle_clock_watts=static * (1.0 - frac),
        **kw,
    )


# ---------------------------------------------------------------------------
# fitted-coefficient persistence (the committed overlay)
# ---------------------------------------------------------------------------


def save_fitted(
    coeffs: PowerCoefficients, out_dir: str | Path = FITTED_DIR,
    meta: dict | None = None,
) -> Path:
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    doc = {
        "name": coeffs.name,
        "coefficients": {
            f: getattr(coeffs, f) for f in (
                *_COEF_FIELDS, "static_watts", "idle_clock_watts",
            )
        },
        "meta": meta or {},
    }
    path = out_dir / f"{coeffs.name}.json"
    path.write_text(json.dumps(doc, indent=2))
    return path


def load_fitted(
    name: str, fitted_dir: str | Path = FITTED_DIR,
) -> PowerCoefficients | None:
    path = Path(fitted_dir) / f"{name}.json"
    if not path.exists():
        return None
    doc = json.loads(path.read_text())
    return PowerCoefficients(name=doc["name"], **doc["coefficients"])
