"""tpusim.serve — simulation-as-a-service daemon (architecture slot L14).

Every entry point before this layer was a one-shot CLI run: each
``simulate``/``faults``/``lint`` invocation pays full process start,
config compose, and trace load, and nothing shares the warm in-memory
result cache across requests.  The daemon composes the pieces PRs 1–4
built — Prometheus text export (:mod:`tpusim.obs.export`), the static
pre-flight (:mod:`tpusim.analysis`), the content-addressed result cache
(:mod:`tpusim.perf`) — behind a stdlib-only JSON-over-HTTP API:

* ``POST /v1/simulate`` — trace ref or inline HLO text + config overlay
  + optional fault schedule → the stats doc, priced through
  :class:`tpusim.perf.CachedEngine` over one process-wide shared
  :class:`~tpusim.perf.ResultCache` (repeat requests are O(lookup));
* ``POST /v1/lint`` — the ``tpusim lint`` diagnostics as JSON;
* ``POST /v1/sweep`` — async link-failure sweeps: returns a job id;
* ``GET /v1/jobs/<id>`` — queued/running/done/failed + result;
* ``GET /healthz`` / ``GET /metrics`` — liveness + Prometheus gauges.

Four internal layers: a registry of pre-loaded trace dirs
(:mod:`.registry`), an admission/queue layer with bounded concurrency,
deadlines, and request-size caps (:mod:`.admission`), a worker layer
that prices through the shared cache (:mod:`.worker`), and the HTTP +
lifecycle layer with SIGTERM drain (:mod:`.daemon`).  ``python -m
tpusim serve`` starts it; :mod:`.client` is the typed urllib client and
``python -m tpusim serve-bench`` (:mod:`.bench`) the loadgen.
"""

from tpusim.serve.admission import (
    AdmissionController,
    DeadlineExceeded,
    Degraded,
    Draining,
    JobTable,
    Overloaded,
)
from tpusim.serve.client import ServeClient, ServeError
from tpusim.serve.daemon import SERVE_FORMAT_VERSION, ServeDaemon
from tpusim.serve.front import FrontSupervisor
from tpusim.serve.hotcache import HotResponseCache
from tpusim.serve.registry import TraceRegistry
from tpusim.serve.supervisor import Supervisor, WorkerTimeout
from tpusim.serve.worker import RequestError, ServeWorker

__all__ = [
    "AdmissionController",
    "DeadlineExceeded",
    "Degraded",
    "Draining",
    "FrontSupervisor",
    "HotResponseCache",
    "JobTable",
    "Overloaded",
    "RequestError",
    "SERVE_FORMAT_VERSION",
    "ServeClient",
    "ServeDaemon",
    "ServeError",
    "ServeWorker",
    "Supervisor",
    "TraceRegistry",
    "WorkerTimeout",
]
