"""Admission control + async job table — the daemon's queueing layer.

The reference's L8 orchestration batches offline jobs (procman's bounded
``parallel``); an online service needs the same bound plus *backpressure
semantics*: a request that cannot run soon must be told so cheaply (429
+ ``Retry-After``), a request that waited past its deadline must fail
predictably (504), and an oversized body must be refused before it is
read (413).  This module owns those decisions; the HTTP layer only maps
the exceptions to status codes.

Model: at most ``max_inflight`` requests execute concurrently; up to
``queue_depth`` more may wait.  A waiter that is still queued at its
deadline raises :class:`DeadlineExceeded`; a request arriving with the
wait queue full raises :class:`Overloaded` (the 429, with a retry hint
derived from the observed service rate); once the daemon starts
draining, everything new raises :class:`Draining` (503) while in-flight
work runs to completion — the SIGTERM contract.

:class:`JobTable` is the async half (``POST /v1/sweep`` → job id →
``GET /v1/jobs/<id>``): a bounded FIFO drained by daemon-owned worker
threads, with terminal results retained for polling.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

__all__ = [
    "AdmissionController",
    "DeadlineExceeded",
    "Degraded",
    "Draining",
    "Job",
    "JobTable",
    "Overloaded",
]


class Overloaded(RuntimeError):
    """Queue full — the 429 with a Retry-After hint."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = max(float(retry_after_s), 1.0)
        super().__init__(
            f"queue full; retry after {self.retry_after_s:.0f}s"
        )


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed while it was queued — the 504."""


class Draining(RuntimeError):
    """The daemon is shutting down and admits nothing new — the 503."""


class Degraded(RuntimeError):
    """The supervised worker pool has fewer live workers than its floor
    — the serve v2 load-shedding 503 + ``Retry-After``.  Queueing into a
    dead pool would convert every request into a slow 504; telling the
    client to come back when the restart backoff opens is cheaper for
    both sides."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = max(float(retry_after_s), 1.0)
        super().__init__(
            f"worker pool degraded; retry after {self.retry_after_s:.0f}s"
        )


class AdmissionController:
    """Bounded inflight + bounded FIFO wait queue with deadlines."""

    def __init__(self, max_inflight: int = 4, queue_depth: int = 16):
        self.max_inflight = max(int(max_inflight), 1)
        self.queue_depth = max(int(queue_depth), 0)
        self._cond = threading.Condition()
        self._inflight = 0
        # FIFO of waiter tokens: a fresh arrival may bypass it only
        # when it is empty, so a queued request can never be starved
        # to its deadline by a steady stream of newcomers
        self._queue: list[object] = []
        self._draining = False
        # observed service rate feeds the Retry-After hint
        self._done = 0
        self._busy_seconds = 0.0

    # -- state ---------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def start_drain(self) -> None:
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float | None = None) -> bool:
        """Block until nothing is in flight or queued (the drain join).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout_s if timeout_s else None
        with self._cond:
            while self._inflight > 0 or self._queue:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cond.wait(remaining if remaining is not None else 0.5)
        return True

    def _retry_after(self) -> float:
        """Hint: how long until a queue slot plausibly frees — the mean
        observed service time times the backlog ahead of a new arrival,
        spread over the inflight lanes."""
        mean_s = (
            self._busy_seconds / self._done if self._done else 1.0
        )
        backlog = self._inflight + len(self._queue)
        return max(mean_s * backlog / self.max_inflight, 1.0)

    # -- the slot ------------------------------------------------------------

    def admit(self, deadline: float | None = None) -> "_Slot":
        """Claim an execution slot, waiting (bounded by ``deadline``, a
        ``time.monotonic()`` instant) for one to free.  Use as a context
        manager::

            with admission.admit(deadline):
                ... do the work ...

        Raises :class:`Overloaded` / :class:`DeadlineExceeded` /
        :class:`Draining` instead of admitting."""
        with self._cond:
            if self._draining:
                raise Draining("server is draining")
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded("deadline expired before admission")
            if self._inflight >= self.max_inflight or self._queue:
                if len(self._queue) >= self.queue_depth:
                    raise Overloaded(self._retry_after())
                token = object()
                self._queue.append(token)
                try:
                    # FIFO: proceed only at the head of the queue AND
                    # with a free lane — a newcomer behind us cannot
                    # overtake, because it queues whenever _queue is
                    # non-empty
                    while (
                        self._queue[0] is not token
                        or self._inflight >= self.max_inflight
                    ):
                        if self._draining:
                            raise Draining("server is draining")
                        remaining = None
                        if deadline is not None:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                raise DeadlineExceeded(
                                    "deadline expired while queued"
                                )
                        self._cond.wait(
                            remaining if remaining is not None else 0.5
                        )
                finally:
                    # success or abandonment (deadline/drain), the token
                    # leaves the line so later waiters can advance
                    self._queue.remove(token)
                    self._cond.notify_all()
            self._inflight += 1
        return _Slot(self)

    def _release(self, busy_s: float) -> None:
        with self._cond:
            self._inflight -= 1
            self._done += 1
            self._busy_seconds += busy_s
            self._cond.notify_all()

    # -- reporting -----------------------------------------------------------

    def stats_dict(self) -> dict[str, float]:
        with self._cond:
            return {
                "inflight": self._inflight,
                "queued": len(self._queue),
                "max_inflight": self.max_inflight,
                "queue_depth": self.queue_depth,
                "completed": self._done,
                "draining": int(self._draining),
            }


class _Slot:
    """One admitted execution; releases on exit and feeds the service-
    rate estimate."""

    __slots__ = ("_adm", "_t0")

    def __init__(self, adm: AdmissionController):
        self._adm = adm
        self._t0 = time.monotonic()

    def __enter__(self) -> "_Slot":
        return self

    def __exit__(self, *exc) -> bool:
        self._adm._release(time.monotonic() - self._t0)
        return False


# ---------------------------------------------------------------------------
# Async jobs
# ---------------------------------------------------------------------------


@dataclass
class Job:
    """One async request (``POST /v1/sweep``)."""

    job_id: str
    kind: str
    request: dict
    status: str = "queued"   # queued | running | done | failed | cancelled
    result: dict | None = None
    error: str | None = None
    submitted_s: float = field(default_factory=time.monotonic)
    finished_s: float | None = None
    #: cooperative-cancellation token (tpusim.guard.CancelToken), minted
    #: at submit so ``DELETE /v1/jobs/<id>`` can trip it whether the job
    #: is still queued or already running — resumable kinds (campaign)
    #: check it at scenario grain and journal everything completed
    cancel_token: object | None = field(default=None, repr=False)

    def to_doc(self) -> dict:
        doc = {
            "job_id": self.job_id,
            "kind": self.kind,
            "status": self.status,
        }
        if self.result is not None:
            doc["result"] = self.result
        if self.error is not None:
            doc["error"] = self.error
        return doc


class JobTable:
    """Bounded FIFO of async jobs + terminal-result retention.

    The daemon owns the worker threads; the table only sequences.  A
    full queue raises :class:`Overloaded` — the same backpressure story
    as the sync path.

    ``persist_dir`` adds crash-safety for the job SPECS: every accepted
    job writes ``<dir>/<job_id>.json`` (atomic temp + ``os.replace``)
    and updates it on state transitions, so a restarted daemon
    re-enqueues whatever was queued or running when the process died —
    an accepted job id must eventually resolve even across a crash.
    (Campaign jobs additionally journal their per-scenario progress via
    :mod:`tpusim.campaign.journal`; requeueing here is what re-enters
    that resume path.)"""

    def __init__(
        self, queue_depth: int = 16, keep: int = 256,
        persist_dir=None, evict_hook=None,
    ):
        self.queue_depth = max(int(queue_depth), 1)
        self.keep = max(int(keep), 1)
        #: called with a job_id when a terminal job ages out of `keep`
        #: — the daemon uses it to reclaim per-job state (campaign
        #: journal dirs) that would otherwise grow without bound
        self.evict_hook = evict_hook
        self._cond = threading.Condition()
        self._queue: list[Job] = []
        self._jobs: dict[str, Job] = {}
        self._next_id = 0
        self._draining = False
        self.persist_dir = Path(persist_dir) if persist_dir else None
        self.recovered = 0
        if self.persist_dir is not None:
            os.makedirs(self.persist_dir, exist_ok=True)
            self._recover()

    # -- persistence ---------------------------------------------------------

    def _job_path(self, job_id: str):
        return self.persist_dir / f"{job_id}.json"

    def _persist(self, job: Job) -> None:
        """Write the job's current state (caller holds the lock)."""
        if self.persist_dir is None:
            return
        doc = {
            "job_id": job.job_id,
            "kind": job.kind,
            "request": job.request,
            "status": job.status,
        }
        if job.result is not None:
            doc["result"] = job.result
        if job.error is not None:
            doc["error"] = job.error
        path = self._job_path(job.job_id)
        tmp = path.with_suffix(
            f".tmp.{os.getpid()}.{threading.get_ident()}"
        )
        tmp.write_text(json.dumps(doc, sort_keys=True) + "\n")
        # lint-allow: TL352 best-effort job persist — boot quarantines
        # a torn file with one warning and recovery continues
        os.replace(tmp, path)

    def _unpersist(self, job_id: str) -> None:
        if self.persist_dir is None:
            return
        try:
            self._job_path(job_id).unlink()
        except OSError:
            pass

    def _quarantine(self, path, reason: str) -> None:
        """Move one torn/corrupt persist file aside (a single warning;
        the file keeps its name under ``quarantine/`` for forensics)
        so every later boot recovers cleanly instead of re-warning —
        or worse, aborting — on the same damage."""
        import warnings

        target = self.persist_dir / "quarantine" / path.name
        try:
            os.makedirs(target.parent, exist_ok=True)
            # lint-allow: TL352 quarantine MOVE of damage already on
            # disk, not a staged publish
            os.replace(path, target)
            moved = f"quarantined to {target.parent.name}/{target.name}"
        except OSError:
            moved = "left in place (quarantine move failed)"
        warnings.warn(
            f"job table: persisted job {path.name} is unreadable "
            f"({reason}); {moved}, recovery continues with the "
            f"healthy jobs",
            RuntimeWarning, stacklevel=3,
        )

    def _recover(self) -> None:
        """Reload persisted jobs: terminal ones return to the polling
        table, queued/RUNNING ones re-enqueue (a job that was mid-run
        when the daemon died must run again — resumable kinds pick up
        from their own journal).  A torn or corrupt per-job file —
        a daemon killed mid-``_persist`` before the atomic replace, or
        disk damage — quarantines with ONE warning and recovery
        continues: one bad file must never take down the healthy
        jobs' crash-safety."""
        from tpusim.guard.cancel import CancelToken

        for path in sorted(self.persist_dir.glob("job-*.json")):
            try:
                doc = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError, ValueError) as e:
                self._quarantine(path, f"{type(e).__name__}: {e}")
                continue
            try:
                job = Job(
                    job_id=str(doc["job_id"]),
                    kind=str(doc["kind"]),
                    request=dict(doc["request"]),
                    status=str(doc.get("status", "queued")),
                    result=doc.get("result"),
                    error=doc.get("error"),
                    cancel_token=CancelToken(),
                )
                num = int(job.job_id.rsplit("-", 1)[1])
            except (KeyError, TypeError, ValueError, IndexError,
                    AttributeError) as e:
                self._quarantine(path, f"{type(e).__name__}: {e}")
                continue
            self._next_id = max(self._next_id, num)
            self._jobs[job.job_id] = job
            if job.status in ("queued", "running"):
                job.status = "queued"
                self._queue.append(job)
                self.recovered += 1
                self._persist(job)

    # -- sequencing ----------------------------------------------------------

    def submit(self, kind: str, request: dict) -> Job:
        with self._cond:
            if self._draining:
                raise Draining("server is draining")
            if len(self._queue) >= self.queue_depth:
                raise Overloaded(float(len(self._queue)))
            self._next_id += 1
            from tpusim.guard.cancel import CancelToken

            job = Job(
                job_id=f"job-{self._next_id:06d}", kind=kind,
                request=request, cancel_token=CancelToken(),
            )
            self._queue.append(job)
            self._jobs[job.job_id] = job
            self._persist(job)
            evicted = self._trim()
            self._cond.notify()
        # eviction reclaims arbitrary per-job state (a campaign journal
        # dir can hold thousands of records) — never under the lock,
        # where it would stall every submit/poll/drain on one rmtree
        for jid in evicted:
            if self.evict_hook is not None:
                try:
                    self.evict_hook(jid)
                except Exception:  # noqa: BLE001 - eviction best-effort
                    pass
        return job

    def get(self, job_id: str) -> Job | None:
        with self._cond:
            return self._jobs.get(job_id)

    def next_job(self, timeout_s: float = 0.5) -> Job | None:
        """Pop the next queued job (worker loop); None on timeout."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout_s)
            if not self._queue:
                return None
            job = self._queue.pop(0)
            job.status = "running"
            self._persist(job)
            return job

    def finish(
        self, job: Job, result: dict | None, error: str | None,
        status: str | None = None,
    ) -> None:
        """Land a terminal state.  ``status`` overrides the derived
        done/failed verdict — the job loop passes ``"cancelled"`` when a
        run raised :class:`tpusim.guard.OperationCancelled` (a client
        asked for it; not a failure, not a success)."""
        with self._cond:
            job.status = status or (
                "failed" if error is not None else "done"
            )
            job.result = result
            job.error = error
            job.finished_s = time.monotonic()
            self._persist(job)
            self._cond.notify_all()

    def cancel(self, job_id: str) -> str | None:
        """``DELETE /v1/jobs/<id>``: a queued job lands terminal
        ``cancelled`` immediately; a running job has its token tripped
        and the job loop records ``cancelled`` when the runner unwinds
        (campaign journals guarantee a later resume re-prices nothing
        completed).  Returns the job's (possibly new) status, or None
        for an unknown id.  Terminal jobs are a no-op — cancelling what
        already finished changes nothing."""
        with self._cond:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job.status == "queued":
                try:
                    self._queue.remove(job)
                except ValueError:
                    pass  # a worker popped it between our checks
                else:
                    job.status = "cancelled"
                    job.error = "cancelled by client"
                    job.finished_s = time.monotonic()
                    self._persist(job)
                    self._cond.notify_all()
                    return job.status
            if job.status == "running":
                tok = job.cancel_token
                if tok is not None:
                    tok.cancel("cancelled by client (DELETE /v1/jobs)")
                return "cancelling"
            return job.status

    def start_drain(self) -> None:
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float | None = None) -> bool:
        """Block until no job is queued or running (the drain join:
        queued jobs still execute — an accepted job id must resolve)."""
        deadline = time.monotonic() + timeout_s if timeout_s else None
        with self._cond:
            while any(
                j.status in ("queued", "running")
                for j in self._jobs.values()
            ):
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cond.wait(remaining if remaining is not None else 0.5)
        return True

    def _trim(self) -> list[str]:
        # retain only the newest `keep` terminal jobs; queued/running
        # entries are never dropped.  Returns the evicted ids — the
        # caller runs evict_hook on them OUTSIDE the lock.
        terminal = [
            jid for jid, j in self._jobs.items()
            if j.status in ("done", "failed", "cancelled")
        ]
        evicted: list[str] = []
        while len(terminal) > self.keep:
            dropped = terminal.pop(0)
            self._jobs.pop(dropped, None)
            self._unpersist(dropped)
            evicted.append(dropped)
        return evicted

    def stats_dict(self) -> dict[str, float]:
        with self._cond:
            counts = {"queued": 0, "running": 0, "done": 0, "failed": 0,
                      "cancelled": 0}
            for j in self._jobs.values():
                counts[j.status] = counts.get(j.status, 0) + 1
            return {f"jobs_{k}": v for k, v in counts.items()}
