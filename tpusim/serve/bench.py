"""``tpusim serve-bench`` — the serving layer's measured headline.

Replays a fixture request mix against a daemon at a target concurrency
and reports p50/p95/p99 latency + throughput, next to the cost of the
same work as a one-shot CLI invocation (full process start + config
compose + trace load + pricing).  The subsystem's acceptance number:
a **warm cached** ``POST /v1/simulate`` must be orders of magnitude
faster than the cold CLI path, because the daemon pays parse/compose
once and every repeat request is an engine-cache lookup.

By default the bench boots its own daemon in-process on an ephemeral
loopback port (the same composition ``python -m tpusim serve`` runs);
``--url`` points it at an external one instead, in which case the CLI
baseline is skipped (the fixture may not exist locally).
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from pathlib import Path

__all__ = ["format_report", "run_serve_bench"]

#: the default fixture mix: the multi-device llama fixture is the
#: headline (ISSUE acceptance), the matmul rides along as a second
#: launch class so the cache serves more than one shape
DEFAULT_MIX = (
    {"trace": "llama_tiny_tp2dp2", "arch": "v5p"},
    {"trace": "matmul_512", "arch": "v5e"},
)


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = min(
        int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1,
        len(sorted_vals) - 1,
    )
    return sorted_vals[max(idx, 0)]


def _cli_seconds(trace_path: Path, arch: str, runs: int = 2) -> float:
    """Wall seconds of one cold ``python -m tpusim simulate`` process —
    the best (minimum) of ``runs``, so the reported speedup is the
    conservative one."""
    best = float("inf")
    for _ in range(max(runs, 1)):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "tpusim", "simulate",
             str(trace_path), "--arch", arch],
            capture_output=True, text=True,
        )
        dt = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"CLI baseline failed rc={proc.returncode}: "
                f"{proc.stderr.strip()[:500]}"
            )
        best = min(best, dt)
    return best


def run_serve_bench(
    url: str | None = None,
    trace_root: str | Path | None = None,
    concurrency: int = 8,
    requests: int = 64,
    mix: list[dict] | None = None,
    cli_baseline: bool = True,
    cli_runs: int = 2,
    deadline_s: float = 120.0,
) -> dict:
    """Run the loadgen; returns the report document.

    The measured pass is **warm**: one untimed priming request per mix
    entry runs first, so the reported latencies are the steady-state
    service the daemon exists to provide (the cold numbers are the CLI
    baseline's whole story)."""
    from tpusim.serve.client import ServeClient

    mix = [dict(m) for m in (mix or DEFAULT_MIX)]
    daemon = None
    if url is None:
        from tpusim.serve.daemon import ServeDaemon

        if trace_root is None:
            trace_root = (
                Path(__file__).resolve().parents[2]
                / "tests" / "fixtures" / "traces"
            )
        daemon = ServeDaemon(
            trace_root=trace_root,
            max_inflight=max(int(concurrency), 1),
            queue_depth=max(int(concurrency) * 4, 16),
            deadline_s=deadline_s,
        ).start()
        url = daemon.url
    client = ServeClient(url, timeout_s=deadline_s)

    try:
        # prime: first contact pays trace load + config compose + the
        # cold pricing walk; everything measured after this is warm
        warm_info = []
        for m in mix:
            t0 = time.perf_counter()
            r = client.simulate(**m)
            warm_info.append({
                "request": m,
                "cold_s": time.perf_counter() - t0,
                "cache_hit": r.cache_hit,
            })

        n_total = max(int(requests), 1)
        n_threads = max(int(concurrency), 1)
        latencies: list[float] = []
        hits = 0
        errors: list[str] = []
        lock = threading.Lock()
        next_idx = [0]

        def loop():
            nonlocal hits
            local_client = ServeClient(url, timeout_s=deadline_s)
            while True:
                with lock:
                    i = next_idx[0]
                    if i >= n_total:
                        return
                    next_idx[0] += 1
                req = mix[i % len(mix)]
                t0 = time.perf_counter()
                try:
                    r = local_client.simulate(**req)
                except Exception as e:  # noqa: BLE001 - report, don't die
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
                    if r.cache_hit:
                        hits += 1

        threads = [
            threading.Thread(target=loop, name=f"serve-bench-{i}")
            for i in range(n_threads)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        latencies.sort()
        doc: dict = {
            "url": url,
            "concurrency": n_threads,
            "requests": len(latencies),
            "errors": errors[:10],
            "wall_s": round(wall, 4),
            "throughput_rps": round(len(latencies) / wall, 2) if wall else 0,
            "cache_hit_fraction": (
                round(hits / len(latencies), 4) if latencies else 0.0
            ),
            "latency_ms": {
                "p50": round(_percentile(latencies, 50) * 1e3, 3),
                "p95": round(_percentile(latencies, 95) * 1e3, 3),
                "p99": round(_percentile(latencies, 99) * 1e3, 3),
                "max": round(latencies[-1] * 1e3, 3) if latencies else 0.0,
            },
            "warmup": warm_info,
        }

        if cli_baseline and trace_root is not None:
            head = mix[0]
            trace_path = Path(trace_root) / str(head.get("trace", ""))
            if trace_path.is_dir():
                cli_s = _cli_seconds(
                    trace_path, str(head.get("arch", "v5p")), runs=cli_runs,
                )
                p50_s = _percentile(latencies, 50)
                doc["cli_baseline"] = {
                    "trace": head.get("trace"),
                    "cold_cli_s": round(cli_s, 4),
                    "warm_p50_ms": doc["latency_ms"]["p50"],
                    "speedup_p50": (
                        round(cli_s / p50_s, 1) if p50_s > 0 else None
                    ),
                }
        return doc
    finally:
        if daemon is not None:
            daemon.drain_and_stop()


def format_report(doc: dict) -> str:
    lines = [
        f"tpusim serve-bench: {doc['requests']} requests @ "
        f"concurrency {doc['concurrency']} against {doc['url']}",
        f"  throughput: {doc['throughput_rps']} req/s "
        f"(wall {doc['wall_s']}s; cache-hit fraction "
        f"{doc['cache_hit_fraction']:.0%})",
        f"  latency: p50 {doc['latency_ms']['p50']}ms  "
        f"p95 {doc['latency_ms']['p95']}ms  "
        f"p99 {doc['latency_ms']['p99']}ms  "
        f"max {doc['latency_ms']['max']}ms",
    ]
    for w in doc.get("warmup", []):
        lines.append(
            f"  cold first request {w['request'].get('trace')}: "
            f"{w['cold_s'] * 1e3:.1f}ms"
        )
    cb = doc.get("cli_baseline")
    if cb:
        lines.append(
            f"  cold CLI simulate ({cb['trace']}): "
            f"{cb['cold_cli_s'] * 1e3:.0f}ms -> warm served p50 "
            f"{cb['warm_p50_ms']}ms = {cb['speedup_p50']}x"
        )
    if doc.get("errors"):
        lines.append(f"  ERRORS ({len(doc['errors'])}): {doc['errors']}")
    return "\n".join(lines)
