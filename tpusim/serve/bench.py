"""``tpusim serve-bench`` — the serving layer's measured headline.

Replays a fixture request mix against a daemon at a target concurrency
and reports p50/p95/p99 latency + throughput, next to the cost of the
same work as a one-shot CLI invocation (full process start + config
compose + trace load + pricing).  The subsystem's acceptance number:
a **warm cached** ``POST /v1/simulate`` must be orders of magnitude
faster than the cold CLI path, because the daemon pays parse/compose
once and every repeat request is an engine-cache lookup.

By default the bench boots its own daemon in-process on an ephemeral
loopback port (the same composition ``python -m tpusim serve`` runs);
``--url`` points it at an external one instead, in which case the CLI
baseline is skipped (the fixture may not exist locally).
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from pathlib import Path

__all__ = ["format_acceptor_sweep", "format_report", "format_sweep",
           "run_acceptor_sweep", "run_serve_bench", "run_worker_sweep"]

#: the default fixture mix: the multi-device llama fixture is the
#: headline (ISSUE acceptance), the matmul rides along as a second
#: launch class so the cache serves more than one shape
DEFAULT_MIX = (
    {"trace": "llama_tiny_tp2dp2", "arch": "v5p"},
    {"trace": "matmul_512", "arch": "v5e"},
)


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = min(
        int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1,
        len(sorted_vals) - 1,
    )
    return sorted_vals[max(idx, 0)]


def _cli_seconds(trace_path: Path, arch: str, runs: int = 2) -> float:
    """Wall seconds of one cold ``python -m tpusim simulate`` process —
    the best (minimum) of ``runs``, so the reported speedup is the
    conservative one."""
    best = float("inf")
    for _ in range(max(runs, 1)):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "tpusim", "simulate",
             str(trace_path), "--arch", arch],
            capture_output=True, text=True,
        )
        dt = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"CLI baseline failed rc={proc.returncode}: "
                f"{proc.stderr.strip()[:500]}"
            )
        best = min(best, dt)
    return best


def _run_storm(
    url: str, mix: list[dict], n_total: int, n_threads: int,
    deadline_s: float,
) -> tuple[list[float], int, list[str], float]:
    """One concurrent request storm: ``n_total`` requests round-robined
    over the mix from ``n_threads`` client threads.  Returns
    ``(latencies, cache_hits, errors, wall_s)``."""
    from tpusim.serve.client import ServeClient

    latencies: list[float] = []
    hits = [0]
    errors: list[str] = []
    lock = threading.Lock()
    next_idx = [0]

    def loop():
        local_client = ServeClient(url, timeout_s=deadline_s)
        while True:
            with lock:
                i = next_idx[0]
                if i >= n_total:
                    return
                next_idx[0] += 1
            req = mix[i % len(mix)]
            t0 = time.perf_counter()
            try:
                r = local_client.simulate(**req)
            except Exception as e:  # noqa: BLE001 - report, don't die
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                continue
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
                if r.cache_hit:
                    hits[0] += 1

    threads = [
        threading.Thread(target=loop, name=f"serve-bench-{i}")
        for i in range(max(n_threads, 1))
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return latencies, hits[0], errors, wall


def _boot_daemon_proc(
    trace_root, concurrency, deadline_s, serve_workers,
    acceptors: int = 0, hot_cache_dir=None,
):
    """Boot ``python -m tpusim serve`` as its own process; returns
    ``(proc, url)``.  The sweep measures the daemon as deployed — in its
    own process — because an in-process daemon shares the loadgen's GIL,
    and the pool legs then measure loadgen contention, not the pool."""
    import re
    import subprocess
    import sys

    cmd = [
        sys.executable, "-m", "tpusim", "serve",
        "--host", "127.0.0.1", "--port", "0",
        "--trace-root", str(trace_root),
        "--max-inflight", str(max(int(concurrency), 1)),
        "--queue-depth", str(max(int(concurrency) * 4, 16)),
        "--deadline-s", str(float(deadline_s)),
    ]
    if serve_workers > 0:
        cmd += ["--serve-workers", str(int(serve_workers))]
    if acceptors > 0:
        cmd += ["--acceptors", str(int(acceptors))]
    if hot_cache_dir is not None:
        cmd += ["--hot-cache", str(hot_cache_dir)]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()  # the bound-port startup contract
    m = re.search(r"http://[\d.:]+", line or "")
    if m is None:
        proc.kill()
        proc.wait()
        raise RuntimeError(
            f"serve daemon never printed its URL (got {line!r})"
        )
    return proc, m.group(0)


def run_serve_bench(
    url: str | None = None,
    trace_root: str | Path | None = None,
    concurrency: int = 8,
    requests: int = 64,
    mix: list[dict] | None = None,
    cli_baseline: bool = True,
    cli_runs: int = 2,
    deadline_s: float = 120.0,
    serve_workers: int = 0,
    reps: int = 1,
    out_of_process: bool = False,
) -> dict:
    """Run the loadgen; returns the report document.

    The measured pass is **warm**: one untimed priming request per mix
    entry runs first, so the reported latencies are the steady-state
    service the daemon exists to provide (the cold numbers are the CLI
    baseline's whole story)."""
    from tpusim.serve.client import ServeClient

    mix = [dict(m) for m in (mix or DEFAULT_MIX)]
    daemon = None
    daemon_proc = None
    if url is None:
        if trace_root is None:
            trace_root = (
                Path(__file__).resolve().parents[2]
                / "tests" / "fixtures" / "traces"
            )
        if out_of_process:
            daemon_proc, url = _boot_daemon_proc(
                trace_root, concurrency, deadline_s,
                max(int(serve_workers), 0),
            )
        else:
            from tpusim.serve.daemon import ServeDaemon

            daemon = ServeDaemon(
                trace_root=trace_root,
                max_inflight=max(int(concurrency), 1),
                queue_depth=max(int(concurrency) * 4, 16),
                deadline_s=deadline_s,
                serve_workers=max(int(serve_workers), 0),
            ).start()
            url = daemon.url
    client = ServeClient(url, timeout_s=deadline_s)

    try:
        # prime: first contact pays trace load + config compose + the
        # cold pricing walk; everything measured after this is warm
        warm_info = []
        for m in mix:
            t0 = time.perf_counter()
            r = client.simulate(**m)
            warm_info.append({
                "request": m,
                "cold_s": time.perf_counter() - t0,
                "cache_hit": r.cache_hit,
            })

        n_total = max(int(requests), 1)
        n_threads = max(int(concurrency), 1)

        # steady-state warmup: under serve v2 each WORKER owns its own
        # registry + L1, and work-conserving dispatch spills a busy
        # home's requests to its neighbors — an untimed concurrent
        # storm pushes every worker through its cold parse so the
        # measured pass is the steady-state service, not a parse bench
        n_warm = max(n_threads * 2, len(mix) * 2, serve_workers * 2)
        _run_storm(url, mix, n_warm, n_threads, deadline_s)

        # reps > 1: repeat the measured storm and keep the
        # best-throughput pass — shared CI containers are noisy
        # neighbors, and the steady-state capability (not the worst
        # co-tenant interference window) is the number the scaling
        # claim is about; errors from EVERY pass are kept
        best = None
        errors: list[str] = []
        for _ in range(max(int(reps), 1)):
            latencies, hits, errs, wall = _run_storm(
                url, mix, n_total, n_threads, deadline_s,
            )
            errors.extend(errs)
            if best is None or (
                wall > 0 and len(latencies) / wall > best[3]
            ):
                rps = len(latencies) / wall if wall else 0.0
                best = (latencies, hits, wall, rps)
        latencies, hits, wall, _rps = best
        latencies.sort()
        doc: dict = {
            "url": url,
            "concurrency": n_threads,
            "serve_workers": max(int(serve_workers), 0),
            "requests": len(latencies),
            "error_count": len(errors),
            "errors": errors[:10],   # sample only — error_count is the truth
            "wall_s": round(wall, 4),
            "throughput_rps": round(len(latencies) / wall, 2) if wall else 0,
            "cache_hit_fraction": (
                round(hits / len(latencies), 4) if latencies else 0.0
            ),
            "latency_ms": {
                "p50": round(_percentile(latencies, 50) * 1e3, 3),
                "p95": round(_percentile(latencies, 95) * 1e3, 3),
                "p99": round(_percentile(latencies, 99) * 1e3, 3),
                "max": round(latencies[-1] * 1e3, 3) if latencies else 0.0,
            },
            "warmup": warm_info,
        }

        if cli_baseline and trace_root is not None:
            head = mix[0]
            trace_path = Path(trace_root) / str(head.get("trace", ""))
            if trace_path.is_dir():
                cli_s = _cli_seconds(
                    trace_path, str(head.get("arch", "v5p")), runs=cli_runs,
                )
                p50_s = _percentile(latencies, 50)
                doc["cli_baseline"] = {
                    "trace": head.get("trace"),
                    "cold_cli_s": round(cli_s, 4),
                    "warm_p50_ms": doc["latency_ms"]["p50"],
                    "speedup_p50": (
                        round(cli_s / p50_s, 1) if p50_s > 0 else None
                    ),
                }
        if daemon is not None and daemon.supervisor is not None:
            sup = daemon.supervisor.stats_dict()
            doc["workers"] = {
                "alive": sup["workers_alive"],
                "restarts": sup["worker_restarts_total"],
                "kills": sup["worker_kills_total"],
                "retries": sup["worker_retries_total"],
                "dispatched": sup["worker_dispatched_total"],
            }
        elif daemon_proc is not None and serve_workers > 0:
            # out-of-process: the fleet state rides /healthz + /metrics
            health = client.healthz()
            worker_docs = health.get("workers") or []
            retries = 0
            try:
                for line in client.metrics_text().splitlines():
                    if line.startswith("tpusim_serve_worker_retries_total"):
                        retries = int(float(line.split()[1]))
            except Exception:  # noqa: BLE001 - stats garnish, not the bench
                pass
            doc["workers"] = {
                "alive": health.get("workers_alive", 0),
                "restarts": sum(
                    w.get("restarts", 0) for w in worker_docs
                ),
                "kills": sum(w.get("kills", 0) for w in worker_docs),
                "retries": retries,
                "dispatched": sum(
                    w.get("requests_done", 0) for w in worker_docs
                ),
            }
        return doc
    finally:
        if daemon is not None:
            daemon.drain_and_stop()
        if daemon_proc is not None:
            import signal as _signal
            import subprocess as _subprocess

            daemon_proc.send_signal(_signal.SIGTERM)  # the drain path
            try:
                daemon_proc.wait(timeout=30)
            except _subprocess.TimeoutExpired:
                daemon_proc.kill()
                daemon_proc.wait()


def run_worker_sweep(
    worker_counts: list[int] | tuple[int, ...] = (0, 1, 2, 4),
    trace_root: str | Path | None = None,
    concurrency: int = 8,
    requests: int = 64,
    mix: list[dict] | None = None,
    cli_baseline: bool = True,
    cli_runs: int = 2,
    reps: int = 3,
) -> dict:
    """The serve v2 scaling curve: one warm bench pass per worker count
    (``0`` = the single-process path) against a freshly-booted daemon,
    reporting req/s + p50/p95/p99 + error/retry/restart counts per leg
    and each leg's speedup over the single-process baseline.  Every leg
    boots its daemon **out of process** (the deployed topology): an
    in-process daemon shares the loadgen's GIL and the pool legs would
    measure loadgen contention instead of the pool.  The committed
    curve lives in ``reports/serve_bench.json``."""
    counts = sorted({max(int(c), 0) for c in worker_counts})
    if 0 not in counts:
        counts.insert(0, 0)  # the scaling claim needs its baseline
    legs: list[dict] = []
    base_rps = None
    for i, c in enumerate(counts):
        doc = run_serve_bench(
            trace_root=trace_root,
            concurrency=concurrency,
            requests=requests,
            mix=mix,
            cli_baseline=cli_baseline and i == 0,
            cli_runs=cli_runs,
            serve_workers=c,
            reps=reps,
            out_of_process=True,
        )
        leg = {
            "serve_workers": c,
            "throughput_rps": doc["throughput_rps"],
            "latency_ms": doc["latency_ms"],
            "requests": doc["requests"],
            "error_count": doc.get(
                "error_count", len(doc.get("errors", []))
            ),
            "cache_hit_fraction": doc["cache_hit_fraction"],
        }
        if doc.get("workers"):
            leg["worker_restarts"] = doc["workers"]["restarts"]
            leg["worker_retries"] = doc["workers"]["retries"]
        if c == 0:
            base_rps = doc["throughput_rps"]
            if doc.get("cli_baseline"):
                leg["cli_baseline"] = doc["cli_baseline"]
        if base_rps:
            leg["speedup_vs_single_process"] = round(
                doc["throughput_rps"] / base_rps, 2
            )
        legs.append(leg)
    return {
        "concurrency": int(concurrency),
        "requests_per_leg": int(requests),
        # each leg's number is the best of `reps` measured storms
        # against its own freshly-booted out-of-process daemon — the
        # steady-state capability, not the worst co-tenant window of a
        # shared CI box (errors from every rep are still counted)
        "reps_per_leg": max(int(reps), 1),
        "worker_sweep": legs,
        "single_process_rps": base_rps,
        "best_rps": max(leg["throughput_rps"] for leg in legs),
        "best_speedup": max(
            leg.get("speedup_vs_single_process", 1.0) for leg in legs
        ),
    }


def _run_storm_raw(
    url: str, mix: list[dict], n_total: int, n_threads: int,
    deadline_s: float,
) -> tuple[list[float], int, list[str], float]:
    """A storm over pre-serialized keep-alive HTTP — the acceptor-sweep
    loadgen.  The threaded :class:`ServeClient` storm spends more CPU
    (json round trips, dataclass assembly) than a hot-tier server does
    per request; on a small CI box that measures the LOADGEN, not the
    fleet.  Here each thread writes prebuilt request bytes and reads
    Content-Length-delimited responses — the server still parses full
    HTTP and serves real bodies; only the client-side waste is gone.
    Same return contract as :func:`_run_storm`."""
    import json as _json
    import socket
    import urllib.parse

    parsed = urllib.parse.urlsplit(url)
    host, port = parsed.hostname, parsed.port or 80
    reqs = []
    for m in mix:
        body = _json.dumps(
            {"tuned": True, "validate": True, **m}
        ).encode()
        reqs.append(
            b"POST /v1/simulate HTTP/1.1\r\nHost: " + host.encode()
            + b"\r\nContent-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
    latencies: list[float] = []
    hits = [0]
    errors: list[str] = []
    lock = threading.Lock()
    next_idx = [0]

    def loop():
        sock = None
        buf = b""
        while True:
            with lock:
                i = next_idx[0]
                if i >= n_total:
                    break
                next_idx[0] += 1
            req = reqs[i % len(reqs)]
            t0 = time.perf_counter()
            try:
                if sock is None:
                    sock = socket.create_connection(
                        (host, port), timeout=deadline_s,
                    )
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1,
                    )
                    buf = b""
                sock.sendall(req)
                while b"\r\n\r\n" not in buf:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise ConnectionError("server closed")
                    buf += chunk
                head, _, rest = buf.partition(b"\r\n\r\n")
                status = int(head.split(b" ", 2)[1])
                clen = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length"):
                        clen = int(line.split(b":", 1)[1])
                        break
                while len(rest) < clen:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise ConnectionError("server closed mid-body")
                    rest += chunk
                payload, buf = rest[:clen], rest[clen:]
            except OSError as e:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                continue
            dt = time.perf_counter() - t0
            with lock:
                if status != 200:
                    errors.append(f"HTTP {status}: {payload[:120]!r}")
                    continue
                latencies.append(dt)
                if b'"cache_hit": true' in payload:
                    hits[0] += 1
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    threads = [
        threading.Thread(target=loop, name=f"serve-bench-raw-{i}")
        for i in range(max(n_threads, 1))
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return latencies, hits[0], errors, wall


def _storm_proc_main(q, url, mix, n_total, n_threads, deadline_s, raw):
    """One loadgen process of the multi-process storm (acceptor sweep
    legs): runs its share and ships the raw sample back over a queue.
    ALWAYS posts a result — a child that died without posting would
    leave the parent blocked on the queue for the full timeout."""
    try:
        fn = _run_storm_raw if raw else _run_storm
        latencies, hits, errors, wall = fn(
            url, mix, n_total, n_threads, deadline_s,
        )
        q.put((latencies, hits, errors[:20], wall))
    except Exception as e:  # noqa: BLE001 - the child's report boundary
        q.put(([], 0, [f"loadgen child died: {type(e).__name__}: {e}"],
               0.0))


def _run_storm_procs(
    url: str, mix: list[dict], n_total: int, n_threads: int,
    deadline_s: float, procs: int, raw: bool = True,
) -> tuple[list[float], int, list[str], float]:
    """A storm fanned over ``procs`` loadgen PROCESSES.  A threaded
    loadgen caps at its own GIL somewhere past ~1k req/s — measuring a
    multi-acceptor fleet through it would report the loadgen's ceiling,
    not the fleet's.  Throughput uses the storm's outer wall (the
    processes run concurrently)."""
    import multiprocessing

    procs = max(int(procs), 1)
    if procs == 1:
        fn = _run_storm_raw if raw else _run_storm
        return fn(url, mix, n_total, n_threads, deadline_s)
    ctx = multiprocessing.get_context(
        "fork" if "fork" in multiprocessing.get_all_start_methods()
        else "spawn"
    )
    import queue as _queue

    q = ctx.Queue()
    threads_each = max(n_threads // procs, 1)
    # distribute the remainder so the measured sample matches the
    # requested count exactly (a silent floor-division drop would make
    # the report's requests_per_leg a lie)
    shares = [
        n_total // procs + (1 if i < n_total % procs else 0)
        for i in range(procs)
    ]
    children = [
        ctx.Process(
            target=_storm_proc_main,
            args=(q, url, list(mix), share, threads_each, deadline_s,
                  raw),
            daemon=True,
        )
        for share in shares if share > 0
    ]
    t0 = time.perf_counter()
    for p in children:
        p.start()
    latencies: list[float] = []
    hits = 0
    errors: list[str] = []
    for _ in children:
        try:
            lat, h, errs, _w = q.get(timeout=deadline_s + 60)
        except _queue.Empty:
            # a child was killed hard (OOM) before it could post even
            # its failure report — record it and keep the sweep alive
            errors.append("loadgen child never reported (killed?)")
            continue
        latencies.extend(lat)
        hits += h
        errors.extend(errs)
    for p in children:
        p.join(timeout=10)
    wall = time.perf_counter() - t0
    return latencies, hits, errors, wall


def run_acceptor_sweep(
    acceptor_counts: list[int] | tuple[int, ...] = (1, 2, 4),
    trace_root: str | Path | None = None,
    concurrency: int = 8,
    requests: int = 256,
    mix: list[dict] | None = None,
    hot_cache: bool = True,
    serve_workers: int = 0,
    reps: int = 3,
    loadgen_procs: int | None = None,
    deadline_s: float = 120.0,
) -> dict:
    """The serve v3 scaling curve: one warm bench pass per acceptor
    count against a freshly-booted **out-of-process** front fleet
    (``--acceptors N [--hot-cache]``), with the single-process daemon
    (``0``) as the baseline leg.  The loadgen itself fans over
    processes (``loadgen_procs``, default ~half the cores, min 2) so
    its GIL never caps the measurement.  Every leg gets its own hot
    dir: legs must not warm each other."""
    import tempfile

    from tpusim.serve.client import ServeClient

    mix = [dict(m) for m in (mix or DEFAULT_MIX)]
    if trace_root is None:
        trace_root = (
            Path(__file__).resolve().parents[2]
            / "tests" / "fixtures" / "traces"
        )
    if loadgen_procs is None:
        import os as _os

        loadgen_procs = max(min((_os.cpu_count() or 2), 4), 2)
    counts = sorted({max(int(c), 0) for c in acceptor_counts})
    if 0 not in counts:
        counts.insert(0, 0)
    legs: list[dict] = []
    base_rps = None
    for c in counts:
        hot_dir = (
            tempfile.mkdtemp(prefix="tpusim-bench-hot-")
            if hot_cache and c > 0 else None
        )
        proc, url = _boot_daemon_proc(
            trace_root, concurrency, deadline_s,
            serve_workers if c > 0 else 0,
            acceptors=c, hot_cache_dir=hot_dir,
        )
        try:
            client = ServeClient(url, timeout_s=deadline_s, retries=3)
            for m in mix:  # prime every entry (publishes the hot tier)
                client.simulate(**m)
            # untimed steady-state warmup across every acceptor: the
            # kernel distributes connections, so a concurrent storm is
            # what pushes each acceptor through its cold path
            _run_storm_procs(
                url, mix, max(concurrency * 4, (c or 1) * 8),
                concurrency, deadline_s, loadgen_procs,
            )
            best = None
            errors: list[str] = []
            for _ in range(max(int(reps), 1)):
                lat, hits, errs, wall = _run_storm_procs(
                    url, mix, max(int(requests), 1),
                    max(int(concurrency), 1), deadline_s, loadgen_procs,
                )
                errors.extend(errs)
                rps = len(lat) / wall if wall else 0.0
                if best is None or rps > best[3]:
                    best = (lat, hits, wall, rps)
            lat, hits, wall, rps = best
            lat.sort()
            hot_hits = 0
            try:
                for line in client.metrics_text().splitlines():
                    if line.startswith("tpusim_serve_hot_hits_total"):
                        hot_hits = int(float(line.split()[1]))
            except Exception:  # noqa: BLE001 - garnish, not the bench
                pass
            leg = {
                "acceptors": c,
                "hot_cache": bool(hot_dir),
                "serve_workers": serve_workers if c > 0 else 0,
                "throughput_rps": round(rps, 2),
                "requests": len(lat),
                "error_count": len(errors),
                "cache_hit_fraction": (
                    round(hits / len(lat), 4) if lat else 0.0
                ),
                "hot_hits": hot_hits,
                "latency_ms": {
                    "p50": round(_percentile(lat, 50) * 1e3, 3),
                    "p95": round(_percentile(lat, 95) * 1e3, 3),
                    "p99": round(_percentile(lat, 99) * 1e3, 3),
                },
            }
            if c == 0:
                base_rps = leg["throughput_rps"]
            if base_rps:
                leg["speedup_vs_single_process"] = round(
                    leg["throughput_rps"] / base_rps, 2
                )
            legs.append(leg)
        finally:
            import shutil
            import signal as _signal
            import subprocess as _subprocess

            proc.send_signal(_signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except _subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            if hot_dir is not None:
                # per-leg tempdir holds up to a whole segment; leaking
                # one per leg per run would fill /tmp over time
                shutil.rmtree(hot_dir, ignore_errors=True)
    return {
        "concurrency": int(concurrency),
        "requests_per_leg": int(requests),
        "reps_per_leg": max(int(reps), 1),
        "loadgen_procs": int(loadgen_procs),
        "hot_cache": bool(hot_cache),
        "acceptor_sweep": legs,
        "single_process_rps": base_rps,
        "best_rps": max(leg["throughput_rps"] for leg in legs),
        "best_speedup": max(
            leg.get("speedup_vs_single_process", 1.0) for leg in legs
        ),
    }


def format_acceptor_sweep(doc: dict) -> str:
    lines = [
        f"tpusim serve-bench acceptor sweep @ concurrency "
        f"{doc['concurrency']} ({doc['requests_per_leg']} requests/leg, "
        f"{doc['loadgen_procs']} loadgen procs, "
        f"hot-cache {'on' if doc['hot_cache'] else 'off'})",
        "  acceptors  req/s     p50ms   p95ms   p99ms  errors  speedup",
    ]
    for leg in doc["acceptor_sweep"]:
        lines.append(
            f"  {leg['acceptors']:>9}  {leg['throughput_rps']:>8}  "
            f"{leg['latency_ms']['p50']:>6}  {leg['latency_ms']['p95']:>6}  "
            f"{leg['latency_ms']['p99']:>6}  {leg['error_count']:>6}  "
            f"{leg.get('speedup_vs_single_process', 1.0):>6}x"
        )
    lines.append(
        f"  best: {doc['best_rps']} req/s "
        f"({doc['best_speedup']}x the single-process daemon)"
    )
    return "\n".join(lines)


def format_sweep(doc: dict) -> str:
    lines = [
        f"tpusim serve-bench worker sweep @ concurrency "
        f"{doc['concurrency']} ({doc['requests_per_leg']} requests/leg)",
        "  workers  req/s     p50ms   p95ms   p99ms  errors  speedup",
    ]
    for leg in doc["worker_sweep"]:
        lines.append(
            f"  {leg['serve_workers']:>7}  {leg['throughput_rps']:>8}  "
            f"{leg['latency_ms']['p50']:>6}  {leg['latency_ms']['p95']:>6}  "
            f"{leg['latency_ms']['p99']:>6}  {leg['error_count']:>6}  "
            f"{leg.get('speedup_vs_single_process', 1.0):>6}x"
        )
    lines.append(
        f"  best: {doc['best_rps']} req/s "
        f"({doc['best_speedup']}x the single-process daemon)"
    )
    return "\n".join(lines)


def format_report(doc: dict) -> str:
    lines = [
        f"tpusim serve-bench: {doc['requests']} requests @ "
        f"concurrency {doc['concurrency']} against {doc['url']}",
        f"  throughput: {doc['throughput_rps']} req/s "
        f"(wall {doc['wall_s']}s; cache-hit fraction "
        f"{doc['cache_hit_fraction']:.0%})",
        f"  latency: p50 {doc['latency_ms']['p50']}ms  "
        f"p95 {doc['latency_ms']['p95']}ms  "
        f"p99 {doc['latency_ms']['p99']}ms  "
        f"max {doc['latency_ms']['max']}ms",
    ]
    for w in doc.get("warmup", []):
        lines.append(
            f"  cold first request {w['request'].get('trace')}: "
            f"{w['cold_s'] * 1e3:.1f}ms"
        )
    cb = doc.get("cli_baseline")
    if cb:
        lines.append(
            f"  cold CLI simulate ({cb['trace']}): "
            f"{cb['cold_cli_s'] * 1e3:.0f}ms -> warm served p50 "
            f"{cb['warm_p50_ms']}ms = {cb['speedup_p50']}x"
        )
    if doc.get("errors"):
        lines.append(f"  ERRORS ({len(doc['errors'])}): {doc['errors']}")
    return "\n".join(lines)
