"""Typed client for the :mod:`tpusim.serve` API — stdlib only.

The programmatic counterpart of the curl examples in the README: one
method per route, JSON in/out, server errors surfaced as
:class:`ServeError` carrying the status, the stable error code, and the
diagnostics document when the server attached one (the 400 validation
path).  Used by ``tpusim serve-bench``, the CI serve smoke, and
``tests/test_serve.py`` — the client IS the contract test surface.

Transport: one persistent keep-alive connection per (client, thread)
over :mod:`http.client`, reconnecting transparently when the server
closed it.  A warm request prices in ~1ms server-side; paying a fresh
TCP handshake + connection teardown per call (urllib's behavior) would
cost more than the service itself.

Robustness (serve v2): every call carries a socket timeout (the
constructor default, overridable per call via ``timeout_s=``) so a
stalled daemon can never block the client forever, and **safe**
failures — an idempotent GET, or a POST whose bytes never finished
sending — retry with exponential backoff plus deterministic jitter.  A
non-idempotent POST that finished sending is never replayed: the server
may have executed it, and a re-sent ``/v1/sweep`` would enqueue a
duplicate job.

serve v3 extends the safe set to **idempotent POSTs**: ``/v1/simulate``
and ``/v1/lint`` are pure functions of their body (pricing mutates
nothing), so a connection reset by a recycled acceptor — the multi-
acceptor front SIGKILLs and respawns acceptors under chaos — retries
them transparently on a fresh connection, which the kernel routes to a
surviving acceptor.  ``DELETE /v1/jobs/<id>`` (cancel) is idempotent by
contract (cancelling twice changes nothing) and retries too.  Job
SUBMISSIONS stay never-replayed.
"""

from __future__ import annotations

import hashlib
import http.client
import itertools
import json
import os
import threading
import time
import urllib.parse
from dataclasses import dataclass, field

from tpusim.obs.reqtrace import TRACE_HEADER

__all__ = ["JobStatus", "LintReport", "ServeClient", "ServeError", "SimResult"]


class ServeError(RuntimeError):
    """A non-2xx response from the daemon."""

    def __init__(
        self, status: int, code: str, detail: str,
        doc: dict | None = None, retry_after_s: float | None = None,
    ):
        self.status = int(status)
        self.code = code
        self.detail = detail
        self.doc = doc or {}
        self.retry_after_s = retry_after_s
        super().__init__(f"HTTP {status} {code}: {detail}")

    @property
    def diagnostics(self) -> list[dict]:
        """The TLxxx items of a validation refusal ([] otherwise)."""
        return list(
            (self.doc.get("diagnostics") or {}).get("diagnostics", [])
        )


@dataclass
class SimResult:
    """``POST /v1/simulate`` response."""

    stats: dict
    cache_hit: bool
    trace: str
    arch: str
    num_devices: int
    sim_cycles: float
    model_version: str
    format_version: int


@dataclass
class LintReport:
    """``POST /v1/lint`` response."""

    summary: str
    errors: int
    warnings: int
    diagnostics: dict
    model_version: str

    @property
    def codes(self) -> list[str]:
        return sorted({
            d["code"] for d in self.diagnostics.get("diagnostics", [])
        })


@dataclass
class JobStatus:
    """``GET /v1/jobs/<id>`` response."""

    job_id: str
    status: str        # queued | running | done | failed | cancelled
    result: dict | None = None
    error: str | None = None
    raw: dict = field(default_factory=dict)

    @property
    def terminal(self) -> bool:
        return self.status in ("done", "failed", "cancelled")


#: per-process client counter — the instance half of the jitter salt
_CLIENT_SEQ = itertools.count()


class ServeClient:
    """One daemon endpoint; every method is a single HTTP round trip."""

    def __init__(
        self,
        base_url: str,
        timeout_s: float = 120.0,
        retries: int = 1,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        honor_retry_after: bool = False,
        retry_after_max_s: float = 30.0,
        members: list[str] | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        #: extra attempts for SAFE failures (idempotent GETs and POSTs
        #: whose bytes never finished sending); 0 disables retrying
        self.retries = max(int(retries), 0)
        self.backoff_base_s = max(float(backoff_base_s), 0.0)
        self.backoff_max_s = max(float(backoff_max_s), self.backoff_base_s)
        #: opt-in: honor ``Retry-After`` on 429/503 RESPONSES by
        #: sleeping and re-issuing, up to the same ``retries`` budget.
        #: Safe even for job submissions — a clean 429/503 means the
        #: server REFUSED the request, so re-sending is not a replay
        #: (unlike a transport failure after the bytes left, which
        #: stays never-replayed); timeouts are likewise never retried.
        self.honor_retry_after = bool(honor_retry_after)
        #: ceiling on one honored wait — a server advertising a
        #: pathological hint must not park the client for minutes
        self.retry_after_max_s = max(float(retry_after_max_s), 0.0)
        parsed = urllib.parse.urlsplit(self.base_url)
        if parsed.scheme != "http" or parsed.hostname is None:
            raise ValueError(
                f"base_url must be http://host:port, got {base_url!r}"
            )
        self._host = parsed.hostname
        self._port = parsed.port or 80
        #: cluster failover (serve v4): additional known members, tried
        #: in rotation when the active endpoint refuses or resets a
        #: SAFE request (idempotent, or bytes never finished sending).
        #: The never-replay rules are untouched: a submission that
        #: finished sending, or ANY timeout, never moves to another
        #: node — failover only re-issues what plain retry already
        #: could, just somewhere the connection still opens.
        addrs = [(self._host, self._port)]
        for url in members or []:
            p = urllib.parse.urlsplit(url.rstrip("/"))
            if p.scheme != "http" or p.hostname is None:
                raise ValueError(
                    f"members must be http://host:port, got {url!r}"
                )
            pair = (p.hostname, p.port or 80)
            if pair not in addrs:
                addrs.append(pair)
        self._addrs = addrs
        self._active = 0
        self._local = threading.local()
        # (pid, construction order) — distinct per client instance and
        # per process, yet stable for a given run's construction order,
        # so retry timing stays reproducible within one test run
        self._jitter_salt = f"{os.getpid()}:{next(_CLIENT_SEQ)}"

    # -- transport -----------------------------------------------------------

    def _conn(
        self, fresh: bool = False, timeout_s: float | None = None,
    ) -> http.client.HTTPConnection:
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        host, port = self._addrs[self._active]
        conn = getattr(self._local, "conn", None)
        if conn is not None and (conn.host, conn.port) != (host, port):
            # another thread's failover moved the active member since
            # this thread cached its connection — follow it
            conn.close()
            conn = None
        if conn is None or fresh:
            if conn is not None:
                conn.close()
            conn = http.client.HTTPConnection(host, port, timeout=t)
            self._local.conn = conn
        elif conn.timeout != t:
            # per-call override on a warm keep-alive connection: the
            # timeout lives on the live socket, not just the factory
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
        return conn

    def _backoff_s(self, attempt: int, path: str) -> float:
        """Exponential backoff with deterministic jitter (±25%, derived
        from the client instance + call identity: N identical clients
        retrying the same failed path each land on a DIFFERENT sleep —
        the herd de-synchronizes — while any one client's schedule is
        stable within a run)."""
        base = min(
            self.backoff_base_s * (2.0 ** attempt), self.backoff_max_s,
        )
        h = hashlib.sha256(
            f"{self._jitter_salt}:{path}:{attempt}".encode()
        ).digest()
        return base * (0.75 + 0.5 * int.from_bytes(h[:4], "big") / 0xFFFFFFFF)

    def _raw(
        self, method: str, path: str, body: dict | None = None,
        timeout_s: float | None = None, idempotent: bool = False,
    ):
        data = None
        headers = {"Accept": "application/json"}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        attempt = 0
        fresh = False
        # a REUSED keep-alive socket the server closed between calls is
        # a transport artifact, not a failing server: safe requests get
        # ONE immediate reconnect that neither counts against the retry
        # policy (retries=0 must still survive idle-closed connections,
        # as PR 5's client always did) nor sleeps (a backoff here would
        # tax every request after any idle gap)
        stale_budget = 1
        # one free immediate hop per OTHER known member: a dead node's
        # refused connection must not burn the caller's retry budget
        # just to reach a survivor (retries=0 still fails over)
        failover_budget = len(self._addrs) - 1
        while True:
            was_cached = getattr(self._local, "conn", None) is not None
            conn = self._conn(fresh=fresh, timeout_s=timeout_s)
            sent = False
            try:
                conn.request(method, path, body=data, headers=headers)
                sent = True
                resp = conn.getresponse()
                payload = resp.read()
                # request tracing (off by default server-side): remember
                # the last trace id this thread's requests were assigned
                # so callers can fetch the span tree afterwards
                tid = resp.getheader(TRACE_HEADER)
                if tid:
                    self._local.last_trace_id = tid
                return resp, payload
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, TimeoutError) as e:
                # the server may close an idle keep-alive connection
                # between calls, a daemon may be mid-restart, a stalled
                # one times out.  A non-idempotent request that FINISHED
                # SENDING is never replayed — the server may have
                # executed it (a re-sent /v1/sweep would enqueue a
                # second job) — so only send-stage failures and safe
                # methods retry, with jittered backoff between attempts.
                conn.close()
                self._local.conn = None
                fresh = True
                # idempotent covers simulate/lint POSTs and cancel
                # DELETEs: re-executing them changes nothing server-
                # side, so a connection reset from a recycled acceptor
                # (serve v3 restarts acceptors under it) is retried
                # like any GET — unlike a job submission, which is
                # never replayed once its bytes finished sending.  A
                # TIMEOUT is different even for idempotent bodies: the
                # server may still be executing the slow request, and
                # stacking a replay behind it only compounds the load.
                retryable = (
                    method == "GET"
                    or not sent
                    or (idempotent and not isinstance(e, TimeoutError))
                )
                if (
                    retryable and was_cached and stale_budget > 0
                    and not isinstance(e, TimeoutError)
                ):
                    # a timeout is a real wait, never the stale case
                    stale_budget -= 1
                    continue
                if (
                    retryable and failover_budget > 0
                    and isinstance(e, ConnectionError)
                    and not isinstance(e, TimeoutError)
                ):
                    # connection refused/reset on a SAFE request and
                    # other cluster members are known: rotate to the
                    # next one and re-issue there.  Timeouts never fail
                    # over (the slow node may still be executing) and
                    # non-idempotent requests that finished sending
                    # were already excluded by `retryable`.
                    failover_budget -= 1
                    self._active = (self._active + 1) % len(self._addrs)
                    continue
                if attempt >= self.retries or not retryable:
                    code = (
                        "timeout" if isinstance(e, TimeoutError)
                        else "connection_failed"
                    )
                    raise ServeError(
                        0, code, f"{type(e).__name__}: {e}",
                    ) from None
                time.sleep(self._backoff_s(attempt, path))
                attempt += 1

    def _retry_after_wait_s(self, retry_after_s: float, attempt: int,
                            path: str) -> float:
        """One honored backpressure wait: the server's hint floored by
        the client's own exponential schedule (so repeated refusals
        still back off even under a constant hint), jittered
        deterministically (±25%, the ``_backoff_s`` salt — N clients
        refused together fan back out de-synchronized), capped at
        ``retry_after_max_s``."""
        base = max(float(retry_after_s), self._backoff_s(attempt, path))
        h = hashlib.sha256(
            f"{self._jitter_salt}:ra:{path}:{attempt}".encode()
        ).digest()
        jitter = 0.25 * int.from_bytes(h[:4], "big") / 0xFFFFFFFF
        return min(base * (1.0 + jitter), self.retry_after_max_s)

    def _request(
        self, method: str, path: str, body: dict | None = None,
        timeout_s: float | None = None, idempotent: bool = False,
    ) -> dict:
        attempt = 0
        while True:
            resp, payload = self._raw(
                method, path, body, timeout_s=timeout_s,
                idempotent=idempotent,
            )
            try:
                doc = json.loads(payload or b"{}")
            except (json.JSONDecodeError, ValueError):
                doc = {}
            if resp.status < 400:
                return doc
            retry_after = resp.getheader("Retry-After")
            if (
                self.honor_retry_after
                and resp.status in (429, 503)
                and retry_after is not None
                and attempt < self.retries
            ):
                # a clean backpressure refusal: the server did NOT
                # execute the request, so re-issuing is safe for every
                # route — job submissions included (the never-replay
                # rule guards ambiguous TRANSPORT failures, which
                # _raw still never replays once the bytes left)
                try:
                    hint = float(retry_after)
                except (TypeError, ValueError):
                    hint = 1.0
                time.sleep(self._retry_after_wait_s(hint, attempt, path))
                attempt += 1
                continue
            try:
                # Retry-After may legally be an HTTP-date; surface
                # an unparseable hint as None, never a raw ValueError
                retry_after_s = float(retry_after) \
                    if retry_after is not None else None
            except (TypeError, ValueError):
                retry_after_s = None
            raise ServeError(
                resp.status,
                str(doc.get("error", "http_error")),
                str(doc.get("detail", resp.reason)),
                doc=doc,
                retry_after_s=retry_after_s,
            )

    # -- routes --------------------------------------------------------------

    def healthz(self, timeout_s: float | None = None) -> dict:
        return self._request("GET", "/healthz", timeout_s=timeout_s)

    def metrics_text(self, timeout_s: float | None = None) -> str:
        resp, payload = self._raw("GET", "/metrics", timeout_s=timeout_s)
        if resp.status != 200:
            raise ServeError(resp.status, "http_error", resp.reason)
        return payload.decode()

    def traces(self, timeout_s: float | None = None) -> list[str]:
        return list(
            self._request("GET", "/v1/traces", timeout_s=timeout_s)
            .get("traces", [])
        )

    @property
    def last_trace_id(self) -> str | None:
        """The request-trace id of this THREAD's most recent response,
        or None when the server runs with tracing off (the default)."""
        return getattr(self._local, "last_trace_id", None)

    def recent_traces(
        self, timeout_s: float | None = None,
    ) -> list[dict]:
        """Flight-recorder summaries (slowest-first; the whole fleet's
        when the daemon is a multi-acceptor front).  Requires the
        server to run with ``--trace-requests``."""
        return list(
            self._request(
                "GET", "/v1/debug/traces", timeout_s=timeout_s,
            ).get("traces", [])
        )

    def trace_detail(
        self, trace_id: str, chrome: bool = False,
        timeout_s: float | None = None,
    ) -> dict:
        """One recorded span tree by id (``chrome=True`` returns the
        Perfetto/Chrome ``traceEvents`` document instead)."""
        path = f"/v1/debug/traces/{trace_id}"
        if chrome:
            resp, payload = self._raw(
                "GET", path + "?format=chrome", timeout_s=timeout_s,
            )
            if resp.status != 200:
                raise ServeError(resp.status, "http_error", resp.reason)
            return dict(json.loads(payload))
        return dict(
            self._request("GET", path, timeout_s=timeout_s)
            .get("trace", {})
        )

    def simulate(
        self,
        trace: str | None = None,
        hlo_text: str | None = None,
        arch: str | None = None,
        overlays: list[dict] | None = None,
        faults: dict | None = None,
        tuned: bool = True,
        num_devices: int = 1,
        validate: bool = True,
        deadline_ms: int | None = None,
        timeout_s: float | None = None,
    ) -> SimResult:
        body: dict = {"tuned": tuned, "validate": validate}
        if trace is not None:
            body["trace"] = trace
        if hlo_text is not None:
            body["hlo_text"] = hlo_text
            body["num_devices"] = num_devices
        if arch is not None:
            body["arch"] = arch
        if overlays:
            body["overlays"] = overlays
        if faults is not None:
            body["faults"] = faults
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        doc = self._request(
            "POST", "/v1/simulate", body, timeout_s=timeout_s,
            idempotent=True,
        )
        return SimResult(
            stats=doc["stats"],
            cache_hit=bool(doc["cache_hit"]),
            trace=str(doc["trace"]),
            arch=str(doc["arch"]),
            num_devices=int(doc["num_devices"]),
            sim_cycles=float(doc["sim_cycles"]),
            model_version=str(doc["model_version"]),
            format_version=int(doc["format_version"]),
        )

    def lint(
        self,
        trace: str | None = None,
        hlo_text: str | None = None,
        arch: str | None = None,
        overlays: list[dict] | None = None,
        faults: dict | None = None,
        num_devices: int = 1,
        timeout_s: float | None = None,
    ) -> LintReport:
        body: dict = {}
        if trace is not None:
            body["trace"] = trace
        if hlo_text is not None:
            body["hlo_text"] = hlo_text
            body["num_devices"] = num_devices
        if arch is not None:
            body["arch"] = arch
        if overlays:
            body["overlays"] = overlays
        if faults is not None:
            body["faults"] = faults
        doc = self._request(
            "POST", "/v1/lint", body, timeout_s=timeout_s, idempotent=True,
        )
        return LintReport(
            summary=str(doc["summary"]),
            errors=int(doc["errors"]),
            warnings=int(doc["warnings"]),
            diagnostics=dict(doc["diagnostics"]),
            model_version=str(doc["model_version"]),
        )

    def sweep(self, timeout_s: float | None = None, **request) -> str:
        """Submit an async sweep; returns the job id."""
        doc = self._request(
            "POST", "/v1/sweep", request, timeout_s=timeout_s,
        )
        return str(doc["job_id"])

    def campaign(self, timeout_s: float | None = None, **request) -> str:
        """Submit an async Monte-Carlo campaign (``spec=`` + the usual
        ``trace=``/``hlo_text=``); returns the job id.  Poll with
        :meth:`wait_job` — the result is the campaign report
        document."""
        doc = self._request(
            "POST", "/v1/campaign", request, timeout_s=timeout_s,
        )
        return str(doc["job_id"])

    def fleet(self, timeout_s: float | None = None, **request) -> str:
        """Submit an async fleet digital-twin run (``spec=`` + the
        usual ``trace=``/``hlo_text=``); returns the job id.  Poll
        with :meth:`wait_job` — the result is the fleet capacity
        report document."""
        doc = self._request(
            "POST", "/v1/fleet", request, timeout_s=timeout_s,
        )
        return str(doc["job_id"])

    def advise(self, timeout_s: float | None = None, **request) -> str:
        """Submit an async sharding-advisor sweep (``spec=`` + the
        usual ``trace=``/``hlo_text=``); returns the job id.  Poll
        with :meth:`wait_job` — the result is the ranked advise
        report document."""
        doc = self._request(
            "POST", "/v1/advise", request, timeout_s=timeout_s,
        )
        return str(doc["job_id"])

    def job(self, job_id: str, timeout_s: float | None = None) -> JobStatus:
        doc = self._request(
            "GET", f"/v1/jobs/{job_id}", timeout_s=timeout_s,
        )
        return JobStatus(
            job_id=str(doc["job_id"]),
            status=str(doc["status"]),
            result=doc.get("result"),
            error=doc.get("error"),
            raw=doc,
        )

    def cancel_job(
        self, job_id: str, timeout_s: float | None = None,
    ) -> str:
        """``DELETE /v1/jobs/<id>`` — cooperative cancellation.  A
        queued job is terminal ``cancelled`` on return; a running
        campaign/advise job returns ``cancelling`` and lands terminal
        once the runner unwinds at its next scenario/cell boundary
        (poll with :meth:`wait_job`; completed scenarios stay journaled
        for ``--resume``).  Returns the job's reported status."""
        doc = self._request(
            "DELETE", f"/v1/jobs/{job_id}", timeout_s=timeout_s,
            idempotent=True,
        )
        return str(doc["status"])

    def wait_job(
        self, job_id: str, timeout_s: float = 120.0,
        poll_s: float = 0.1, poll_timeout_s: float | None = None,
    ) -> JobStatus:
        """Poll until the job is terminal; raises TimeoutError.
        ``timeout_s`` bounds the whole wait; ``poll_timeout_s`` is the
        per-poll socket timeout (the constructor default otherwise)."""
        deadline = time.monotonic() + timeout_s
        while True:
            status = self.job(job_id, timeout_s=poll_timeout_s)
            if status.terminal:
                return status
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {status.status!r} after "
                    f"{timeout_s:.0f}s"
                )
            time.sleep(poll_s)
