"""Multi-node serve cluster — membership, trace affinity, heartbeats.

PR 11 scaled serving *within* one box (N acceptor processes, one
public port).  This module scales it *across* boxes with the smallest
protocol that stays split-brain safe:

* **membership** — one node is the cluster primary purely because the
  others were started with ``--join HOST:PORT`` pointing at it.  The
  primary owns a :class:`ClusterRegistry`: a versioned **epoch**
  (monotonic int) plus the member table.  Every mutation — join,
  death, rejoin — bumps the epoch, and the epoch has exactly ONE
  writer (the registry), the same single-writer discipline the PR 11
  JobTable uses for job ids.  Members learn the current view by
  pull-gossip: every heartbeat response carries it.
* **failure detection** — members POST ``/v1/cluster/beat`` every
  ``beat_interval_s``; a member missing ``missed_beats`` consecutive
  deadlines is marked dead and the epoch bumps, so survivors see the
  death on their next beat (the rebroadcast).  A dead node that comes
  back claiming its old epoch is **refused** (409 / :class:`
  StaleEpoch`) — it must rejoin fresh at epoch 0, so a partitioned
  node can never resurrect a stale view of the fleet.
* **affinity** — :class:`AffinityRing` consistent-hashes request
  affinity keys (volatile body keys already stripped by
  :meth:`~tpusim.serve.supervisor.Supervisor.affinity_key`, so the
  key is node-invariant) over the alive members.  Each trace's
  hot/compiled state concentrates on few nodes; when a node dies only
  ITS keys remap (the consistent-hash contract, pinned by test).
* **backoff** — a member that cannot reach the primary retries with
  capped exponential backoff plus seeded jitter (sha256 of
  ``node_id:attempt`` — no ``random`` in serve paths, TL350).

Nothing here prices anything: the cluster is pure control plane.  The
serving data plane (hot cache, result cache, compiled tier) stays
node-local; cross-node traffic is one-hop request forwarding done by
the daemon, never state replication.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import threading
import time

__all__ = [
    "AffinityRing",
    "ClusterRegistry",
    "FORWARD_HEADER",
    "HeartbeatLoop",
    "StaleEpoch",
    "parse_addr",
    "seeded_jitter",
]

#: stamped on cross-node forwarded requests — its presence means "do
#: not forward again", the one-hop guarantee (no routing loops even
#: when two nodes briefly hold different views of the ring)
FORWARD_HEADER = "X-Tpusim-Forwarded"

#: seconds between member heartbeats (and the primary's reap sweeps)
DEFAULT_BEAT_INTERVAL_S = 1.0

#: consecutive missed beats before a member is declared dead
DEFAULT_MISSED_BEATS = 3

#: retry-backoff ceiling for a member that cannot reach the primary
MAX_BEAT_BACKOFF_S = 15.0

#: virtual points per node on the affinity ring — enough that one
#: death spreads its keys roughly evenly over the survivors
RING_REPLICAS = 64


class StaleEpoch(ValueError):
    """A join/beat carried an epoch the registry has moved past."""


def parse_addr(addr: str) -> tuple[str, int]:
    """``HOST:PORT`` → ``(host, port)``; raises ValueError loudly."""
    host, _, port = addr.rpartition(":")
    if not host or not port:
        raise ValueError(f"cluster address wants HOST:PORT, got {addr!r}")
    return host, int(port)


def seeded_jitter(salt: str, attempt: int, base: float) -> float:
    """Deterministic jitter in ``[0, base/4)`` — seeded, not random,
    so chaos tests replay byte-identically (serve discipline TL350)."""
    h = hashlib.sha256(f"{salt}:{attempt}".encode()).digest()
    return 0.25 * base * (int.from_bytes(h[:4], "big") / 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Consistent-hash affinity
# ---------------------------------------------------------------------------


class AffinityRing:
    """Consistent hash of affinity keys over node ids.

    ``RING_REPLICAS`` virtual points per node (sha256 of
    ``"{node_id}#{replica}"``); a key is owned by the first point at
    or after its own hash, wrapping.  Removing a node removes only its
    points, so only keys that node owned remap — the property the
    hot/compiled tiers need to survive a membership change warm.
    """

    def __init__(self, node_ids, replicas: int = RING_REPLICAS):
        points: list[tuple[int, str]] = []
        for nid in sorted(set(node_ids)):
            for r in range(replicas):
                h = hashlib.sha256(f"{nid}#{r}".encode()).digest()
                points.append((int.from_bytes(h[:8], "big"), nid))
        points.sort()
        self._points = points
        self._hashes = [p[0] for p in points]

    def __len__(self) -> int:
        return len({nid for _, nid in self._points})

    def owner(self, key: str) -> str | None:
        """Node id owning ``key`` (None on an empty ring)."""
        if not self._points:
            return None
        x = int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:8], "big"
        )
        i = bisect.bisect_right(self._hashes, x) % len(self._points)
        return self._points[i][1]


# ---------------------------------------------------------------------------
# Primary-side registry (the single epoch writer)
# ---------------------------------------------------------------------------


class ClusterRegistry:
    """Member table + versioned epoch, owned by the cluster primary.

    The primary itself is member zero and never reaped (it IS the
    registry; if it dies the cluster is headless until restart — the
    deliberate simplicity that keeps the epoch single-writer).
    ``clock`` is injectable so tests drive time instead of sleeping.
    """

    def __init__(
        self,
        node_id: str,
        url: str,
        beat_interval_s: float = DEFAULT_BEAT_INTERVAL_S,
        missed_beats: int = DEFAULT_MISSED_BEATS,
        clock=time.monotonic,
    ):
        self.node_id = node_id
        self.beat_interval_s = max(float(beat_interval_s), 0.05)
        self.missed_beats = max(int(missed_beats), 1)
        self._clock = clock
        self._lock = threading.Lock()
        self.epoch = 1
        self._members: dict[str, dict] = {
            node_id: {
                "url": url, "last_beat": clock(),
                "alive": True, "shedding": False,
            },
        }
        self.joins = 0
        self.beats = 0
        self.deaths = 0
        self.stale_rejoins = 0

    # -- mutations (each bumps the epoch) ---------------------------------

    def join(self, node_id: str, url: str, epoch: int = 0) -> dict:
        """Register ``node_id``; returns the new view.

        A fresh join (epoch 0) is always accepted — including a dead
        node coming back, which is exactly the heal path.  A join
        claiming a *stale* nonzero epoch is refused: the node holds an
        outdated picture of the fleet and must rejoin fresh.
        """
        with self._lock:
            if epoch and epoch < self.epoch:
                self.stale_rejoins += 1
                raise StaleEpoch(
                    f"join from {node_id} at stale epoch {epoch} "
                    f"(cluster at {self.epoch}); rejoin with epoch 0"
                )
            self._members[node_id] = {
                "url": url, "last_beat": self._clock(),
                "alive": True, "shedding": False,
            }
            self.epoch += 1
            self.joins += 1
            return self._view_locked()

    def beat(self, node_id: str, epoch: int = 0,
             shedding: bool = False) -> dict:
        """Record a heartbeat; returns the current view (the gossip).

        A beat from a node the registry holds dead (or never met) is
        refused — it was reaped while partitioned and must rejoin
        fresh, never quietly resurrect.
        """
        with self._lock:
            m = self._members.get(node_id)
            if m is None or not m["alive"]:
                self.stale_rejoins += 1
                raise StaleEpoch(
                    f"beat from {node_id} which is not an alive "
                    f"member at epoch {self.epoch}; rejoin with epoch 0"
                )
            m["last_beat"] = self._clock()
            m["shedding"] = bool(shedding)
            self.beats += 1
            return self._view_locked()

    def reap(self) -> list[str]:
        """Mark members past ``missed_beats`` deadlines dead; returns
        the newly dead ids.  One epoch bump covers the whole sweep."""
        deadline = self.beat_interval_s * self.missed_beats
        now = self._clock()
        died: list[str] = []
        with self._lock:
            for nid, m in self._members.items():
                if nid == self.node_id or not m["alive"]:
                    continue
                if now - m["last_beat"] > deadline:
                    m["alive"] = False
                    died.append(nid)
            if died:
                self.epoch += 1
                self.deaths += len(died)
        return died

    # -- views ------------------------------------------------------------

    def _view_locked(self) -> dict:
        return {
            "epoch": self.epoch,
            "beat_interval_s": self.beat_interval_s,
            "missed_beats": self.missed_beats,
            "members": [
                {
                    "node_id": nid, "url": m["url"],
                    "alive": m["alive"], "shedding": m["shedding"],
                }
                for nid, m in sorted(self._members.items())
            ],
        }

    def view(self) -> dict:
        with self._lock:
            return self._view_locked()

    def stats_dict(self) -> dict[str, float]:
        with self._lock:
            alive = sum(1 for m in self._members.values() if m["alive"])
            return {
                "cluster_epoch": float(self.epoch),
                "cluster_joins_total": float(self.joins),
                "cluster_beats_total": float(self.beats),
                "cluster_deaths_total": float(self.deaths),
                "cluster_stale_rejoins_total": float(self.stale_rejoins),
                "cluster_nodes_alive": float(alive),
                "cluster_nodes_configured": float(len(self._members)),
            }


# -- shared view helpers (primary view docs AND gossiped copies) ----------


def alive_members(view: dict | None) -> list[dict]:
    """Alive member entries of a view doc (empty for no view)."""
    if not isinstance(view, dict):
        return []
    return [
        m for m in view.get("members", ())
        if isinstance(m, dict) and m.get("alive")
    ]


def ring_for(view: dict | None, skip_shedding: bool = True) -> AffinityRing:
    """Affinity ring over a view's alive members.

    ``skip_shedding`` drops members currently load-shedding under
    their memory watchdog — the node-grain shed: the ring stops
    forwarding work at a node that is already fighting its RSS, the
    same backpressure the watchdog applies locally.  If everyone
    sheds, fall back to all alive members (never an empty ring while
    someone is up).
    """
    members = alive_members(view)
    if skip_shedding:
        healthy = [m for m in members if not m.get("shedding")]
        if healthy:
            members = healthy
    return AffinityRing([m["node_id"] for m in members])


def member_url(view: dict | None, node_id: str) -> str | None:
    for m in alive_members(view):
        if m.get("node_id") == node_id:
            return m.get("url")
    return None


# ---------------------------------------------------------------------------
# Member-side heartbeat loop
# ---------------------------------------------------------------------------


class HeartbeatLoop:
    """Join-then-beat thread run by every non-primary node.

    On any failure the loop backs off exponentially (capped, seeded
    jitter) and falls back to a fresh join — a 409 means the primary
    holds us dead or our epoch is stale, and the contract for both is
    the same: rejoin at epoch 0.  ``post`` is injectable for tests;
    the default speaks HTTP to ``join_addr``'s public port.
    """

    def __init__(
        self,
        node_id: str,
        url: str,
        join_addr: str,
        interval_s: float = DEFAULT_BEAT_INTERVAL_S,
        timeout_s: float = 2.0,
        post=None,
        on_view=None,
        shedding=None,
    ):
        self.node_id = node_id
        self.url = url
        self.join_addr = join_addr
        self.interval_s = max(float(interval_s), 0.05)
        self.timeout_s = float(timeout_s)
        self._post = post if post is not None else self._http_post
        self._on_view = on_view
        self._shedding = shedding if shedding is not None else (
            lambda: False
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._view: dict | None = None
        self._joined = False
        self._epoch = 0
        self.beats_sent = 0
        self.rejoins = 0

    # -- transport --------------------------------------------------------

    def _http_post(self, path: str, doc: dict):
        import http.client

        host, port = parse_addr(self.join_addr)
        conn = http.client.HTTPConnection(
            host, port, timeout=self.timeout_s,
        )
        try:
            conn.request(
                "POST", path, body=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = None
        return resp.status, parsed

    # -- protocol ---------------------------------------------------------

    def step(self) -> bool:
        """One join-or-beat exchange; True when the view advanced."""
        if not self._joined:
            status, doc = self._post("/v1/cluster/join", {
                "node_id": self.node_id, "url": self.url,
                "epoch": self._epoch,
            })
            if status == 409:
                # our epoch is stale: drop it and rejoin fresh
                self._epoch = 0
                return False
            if status != 200 or not isinstance(doc, dict):
                return False
            self._joined = True
            self.rejoins += 1
        else:
            status, doc = self._post("/v1/cluster/beat", {
                "node_id": self.node_id, "epoch": self._epoch,
                "shedding": bool(self._shedding()),
            })
            if status == 409:
                # the primary reaped us while we were partitioned;
                # the ONLY legal recovery is a fresh join
                self._joined = False
                self._epoch = 0
                return False
            if status != 200 or not isinstance(doc, dict):
                self._joined = False
                return False
            self.beats_sent += 1
        epoch = doc.get("epoch")
        if isinstance(epoch, int) and epoch >= self._epoch:
            self._epoch = epoch
            with self._lock:
                self._view = doc
            if self._on_view is not None:
                self._on_view(doc)
        return True

    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            try:
                ok = self.step()
            except (OSError, ValueError):
                ok = False
                self._joined = False
            if ok:
                attempt = 0
                delay = self.interval_s
            else:
                attempt += 1
                base = min(
                    self.interval_s * (2.0 ** (attempt - 1)),
                    MAX_BEAT_BACKOFF_S,
                )
                delay = base + seeded_jitter(self.node_id, attempt, base)
            if self._stop.wait(delay):
                return

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "HeartbeatLoop":
        self._thread = threading.Thread(
            target=self._run, name="tpusim-cluster-beat", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def view(self) -> dict | None:
        with self._lock:
            return self._view

    @property
    def joined(self) -> bool:
        return self._joined
