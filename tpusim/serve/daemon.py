"""The HTTP + lifecycle layer of :mod:`tpusim.serve`.

Stdlib only: :class:`http.server.ThreadingHTTPServer` accepts, one
thread per connection; the admission layer (not the thread count)
bounds real concurrency.  The handler does protocol work exclusively —
route, size-cap, parse, map exceptions to status codes — and delegates
every decision to the layers below:

====================  ====  =====================================
route                 verb  backing layer
====================  ====  =====================================
``/v1/simulate``      POST  admission → :meth:`ServeWorker.simulate`
``/v1/lint``          POST  admission → :meth:`ServeWorker.lint`
``/v1/sweep``         POST  :class:`JobTable` (async; returns job id)
``/v1/campaign``      POST  :class:`JobTable` (async; crash-safe when
                            ``--state-dir`` is set — spec persisted,
                            progress journaled, restart resumes)
``/v1/advise``        POST  :class:`JobTable` (async; the sharding
                            advisor's ranked strategy-sweep report)
``/v1/fleet``         POST  :class:`JobTable` (async; the fleet
                            digital twin's capacity report — crash-
                            safe like campaign jobs: spec persisted,
                            pricing journaled, restart resumes)
``/v1/jobs/<id>``     GET   :class:`JobTable`
``/v1/jobs/<id>``     DEL   :class:`JobTable` (cooperative cancel —
                            queued jobs land ``cancelled`` at once,
                            running campaign/advise jobs unwind at
                            their next scenario/cell boundary with
                            completed work journaled)
``/v1/traces``        GET   :class:`TraceRegistry`
``/healthz``          GET   liveness (503 while draining)
``/metrics``          GET   Prometheus via ``obs.export.prometheus_text``
====================  ====  =====================================

Status mapping: :class:`~tpusim.serve.worker.RequestError` carries its
own status (400/404/422), :class:`Overloaded` → 429 + ``Retry-After``,
:class:`DeadlineExceeded` → 504, :class:`Draining` → 503, an oversized
body → 413 before it is read.  Every JSON response carries
``format_version``, ``model_version``, and (simulate) ``cache_hit`` so
clients can reason about staleness.

Lifecycle (the SIGTERM contract): stop admitting, let in-flight
requests and accepted jobs run to completion, flush the disk tier of
the shared result cache, close the listener, exit 0.  ``/healthz``
reports 503 from the first drain instant so load balancers stop
routing before the listener disappears.

serve v3 (the multi-acceptor front tier, :mod:`tpusim.serve.front`):
one ``ServeDaemon`` per **acceptor process**, each parsing + admitting
on its own GIL.  Three additions engage only in that topology (or when
``hot_cache`` is mounted standalone):

* the **hot-response path**: a ``POST /v1/simulate`` whose affinity key
  is published in the shared :class:`~tpusim.serve.hotcache.
  HotResponseCache` is answered straight from the mmap — no admission,
  no dispatch, no re-serialization (the stored bytes ARE the final
  envelope, ``cache_hit`` true);
* a **direct listener** on an ephemeral port for fleet-internal traffic
  (peer ``/-/stats`` merges, job proxying to the primary acceptor);
* **fleet views**: ``/metrics`` and ``/healthz`` merge every live
  acceptor's local values into one document (``?scope=local`` keeps a
  single acceptor's view reachable).
"""

from __future__ import annotations

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpusim.guard.cancel import CancelToken, OperationCancelled
from tpusim.obs.reqtrace import TRACE_HEADER
from tpusim.serve.admission import (
    AdmissionController,
    DeadlineExceeded,
    Degraded,
    Draining,
    JobTable,
    Overloaded,
)
from tpusim.serve.cluster import (
    DEFAULT_BEAT_INTERVAL_S,
    DEFAULT_MISSED_BEATS,
    FORWARD_HEADER,
    StaleEpoch,
    alive_members,
    member_url,
    ring_for,
)
from tpusim.serve.registry import TraceRegistry
from tpusim.serve.supervisor import (
    CooperativeCancel,
    Supervisor,
    WorkerTimeout,
)
from tpusim.serve.worker import MAX_DEADLINE_S, RequestError, ServeWorker

__all__ = ["SERVE_FORMAT_VERSION", "ServeDaemon"]

#: bumped when the response document shape changes
SERVE_FORMAT_VERSION = 1

#: default request-body cap (inline HLO fits; a runaway upload does not)
DEFAULT_MAX_REQUEST_BYTES = 8 * 1024 * 1024


def _prewarm_pricing_stack() -> None:
    """Pull the request path's one-time costs forward to boot.

    A cold first request used to pay them inside its own latency
    budget: the pricing-backend resolution (a numpy import + a native
    dlopen, ~0.5 s on a cold container) and the lazy imports the
    simulate path performs (driver, faults, analysis passes — tens of
    ms of bytecode work when no .pyc is cached).  Called at daemon
    start and worker boot; everything here is idempotent."""
    from tpusim.fastpath.price import resolve_backend

    resolve_backend(None)
    import tpusim.analysis.config_passes  # noqa: F401
    import tpusim.faults  # noqa: F401
    import tpusim.sim.driver  # noqa: F401
    from tpusim.timing.model_version import model_version

    model_version()  # memoized source-hash pass


def _get_route(path: str) -> str:
    """Histogram/access-log route label for a GET path — a small fixed
    vocabulary, never raw paths (unbounded label cardinality would let
    one curl loop grow /metrics without bound)."""
    if path == "/healthz":
        return "healthz"
    if path == "/metrics":
        return "metrics"
    if path == "/v1/traces":
        return "traces"
    if path.startswith("/v1/debug/traces"):
        return "debug"
    if path.startswith("/v1/jobs/"):
        return "jobs"
    return "other"


def _post_route(path: str) -> str:
    """Route label for a POST path (same fixed-vocabulary rule)."""
    if path in ("/v1/simulate", "/v1/lint", "/v1/sweep", "/v1/campaign",
                "/v1/advise", "/v1/fleet"):
        return path.rsplit("/", 1)[1]
    return "other"


class _Handler(BaseHTTPRequestHandler):
    """Protocol-only; all policy lives in the daemon's layers."""

    #: set per-daemon via the dynamic subclass in ServeDaemon.start
    daemon_obj: "ServeDaemon" = None
    protocol_version = "HTTP/1.1"
    # small JSON responses after sub-ms pricing: waiting out Nagle/
    # delayed-ACK would dominate the latency the cache just earned
    disable_nagle_algorithm = True
    # per-connection socket READ timeout: a client that sends headers
    # and then stalls (or an idle keep-alive) would otherwise pin a
    # handler thread forever — body reads happen BEFORE admission, so
    # no admission bound covers them.  http.server catches the timeout
    # in handle_one_request and closes the connection; in-flight
    # pricing is unaffected (no read is outstanding while we work).
    timeout = 60.0

    # request-trace state, reset per request in parse_request (one
    # handler instance serves every request on a keep-alive connection)
    _trace = None
    _route = None
    _parse_t0 = None
    _finished_tid = None
    _relay_tid = None

    # -- plumbing ------------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        d = self.daemon_obj
        if d is not None and d.verbose:
            super().log_message(fmt, *args)

    def parse_request(self):
        # stamped AFTER the request line was read (so keep-alive idle
        # time between requests never pollutes the http_parse span) and
        # only when some observability surface is on — tracing off
        # means this hook costs one attribute test per request
        self._trace = None
        self._route = None
        self._parse_t0 = None
        self._finished_tid = None
        self._relay_tid = None
        d = self.daemon_obj
        if d is not None and (
            d.reqtrace is not None or d.access_log is not None
        ):
            self._parse_t0 = time.monotonic()
        return super().parse_request()

    def _track(self, route: str) -> None:
        """Begin per-request observability for a *counted* request —
        called exactly where ``serve_requests_total`` increments, so
        the latency histograms' counts sum to that counter."""
        d = self.daemon_obj
        self._route = route
        rt = d.reqtrace
        if rt is None:
            return
        tr = rt.begin(
            route, self.headers.get(TRACE_HEADER),
            start_s=self._parse_t0,
        )
        acc = d.pop_accept_ts(self.connection)
        if acc is not None:
            # fd-passing front: the parent's accept timestamp rode the
            # send_fds message; the span covers accept -> child recv
            tr.note_fd_dispatch(acc[0], acc[1])
        if self._parse_t0 is not None:
            tr.add_span(
                "http_parse", self._parse_t0,
                time.monotonic() - self._parse_t0,
            )
        self._trace = tr

    def _finalize(self, status: int) -> str | None:
        """Complete per-request observability (idempotent; called by
        every send helper, possibly twice for early-observing routes
        like ``/metrics``).  Returns the trace ID for the response
        header, if any."""
        d = self.daemon_obj
        tr = self._trace
        if tr is not None:
            self._trace = None
            doc = d.reqtrace.finish(tr, status)
            self._finished_tid = tr.trace_id
            if d.access_log is not None:
                self._route = None
                d.access_log.write(
                    route=tr.route, status=status,
                    latency_ms=doc["total_ms"], trace_id=tr.trace_id,
                    tier=(doc.get("meta") or {}).get("tier"),
                    acceptor=d.acceptor_index,
                    node_id=d.cluster_node_id,
                )
            return self._finished_tid
        if self._finished_tid is not None:
            return self._finished_tid
        if self._relay_tid is not None:
            return self._relay_tid
        if d.access_log is not None and self._route is not None:
            route = self._route
            self._route = None  # one access-log line per request
            latency_ms = (
                (time.monotonic() - self._parse_t0) * 1000.0
                if self._parse_t0 is not None else 0.0
            )
            d.access_log.write(
                route=route, status=status, latency_ms=latency_ms,
                acceptor=d.acceptor_index, node_id=d.cluster_node_id,
            )
        return None

    def _send_json(
        self, status: int, doc: dict, headers: dict | None = None,
    ) -> None:
        d = self.daemon_obj
        tr = self._trace
        t_resp = time.monotonic() if tr is not None else 0.0
        body = json.dumps({
            "format_version": SERVE_FORMAT_VERSION,
            "model_version": d.worker.model_version,
            **doc,
        }, sort_keys=True).encode() + b"\n"
        if tr is not None:
            tr.add_span("respond", t_resp, time.monotonic() - t_resp)
        tid = self._finalize(status)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if tid:
            self.send_header(TRACE_HEADER, tid)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the work is done either way
        d._count_status(status)

    def _send_body(self, status: int, body) -> None:
        """Pre-serialized JSON body: a supervised worker's ok_bytes
        response, or a hot-cache ``memoryview`` — both already carry the
        format/model_version envelope.  A memoryview goes to the socket
        without an intermediate copy (the serve v3 zero-copy path)."""
        tid = self._finalize(status)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if tid:
            self.send_header(TRACE_HEADER, tid)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        self.daemon_obj._count_status(status)

    def _send_text(self, status: int, text: str, content_type: str) -> None:
        body = text.encode()
        tid = self._finalize(status)
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if tid:
            self.send_header(TRACE_HEADER, tid)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        self.daemon_obj._count_status(status)

    def _read_body(self) -> dict | None:
        """Size-capped JSON body; sends the error response itself and
        returns None on refusal."""
        tr = self._trace
        if tr is None:
            return self._read_body_inner()
        with tr.span("parse"):
            return self._read_body_inner()

    def _read_body_inner(self) -> dict | None:
        d = self.daemon_obj
        try:
            length = int(self.headers.get("Content-Length", "0") or "0")
        except ValueError:
            length = -1
        if length < 0:
            self._send_json(411, {
                "error": "length_required",
                "detail": "Content-Length is required",
            })
            return None
        if length > d.max_request_bytes:
            # refuse BEFORE reading; the unread body makes the
            # connection unusable, so close it
            self.close_connection = True
            self._send_json(413, {
                "error": "request_too_large",
                "detail": (
                    f"body is {length} bytes; this server caps requests "
                    f"at {d.max_request_bytes}"
                ),
            }, headers={"Connection": "close"})
            return None
        raw = self.rfile.read(length) if length else b"{}"
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            self._send_json(400, {
                "error": "bad_json", "detail": f"body is not JSON: {e}",
            })
            return None
        if not isinstance(body, dict):
            self._send_json(400, {
                "error": "bad_json", "detail": "body must be a JSON object",
            })
            return None
        return body

    # -- routes --------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 - stdlib signature
        d = self.daemon_obj
        path, _, query = self.path.partition("?")
        path = path.rstrip("/") or "/"
        local = "scope=local" in query
        if path.startswith("/v1/cluster/"):
            # cluster control traffic is not user traffic (the
            # /-/stats discipline at node grain): uncounted, untraced
            self._cluster_get(path)
            return
        # fleet-internal probes (/-/stats, ?scope=local merges) are not
        # traffic: counting them would inflate the fleet-summed request
        # counters by N-1 on every scrape/health poll
        if path != "/-/stats" and not local:
            d._count("serve_requests_total")
            self._track(_get_route(path))
        if path == "/healthz":
            if d.admission.draining:
                self._send_json(503, {"status": "draining"})
            elif d.cluster_active() and not local:
                self._send_json(200, d.cluster_healthz())
            elif d.in_fleet and not local:
                self._send_json(200, d.fleet_healthz())
            else:
                self._send_json(200, d.local_healthz())
        elif path == "/metrics":
            d._count("serve_requests_metrics_total")
            if self._trace is not None:
                # observe THIS scrape before rendering, so the
                # histogram bucket counts in the document it returns
                # sum exactly to serve_requests_total (finalize is
                # idempotent; _send_text reuses the frozen trace ID)
                self._finalize(200)
            if d.cluster_active() and not local:
                text = d.cluster_metrics_text()
            elif d.in_fleet and not local:
                text = d.fleet_metrics_text()
            else:
                text = d.metrics_text()
            self._send_text(200, text, "text/plain; version=0.0.4")
        elif path == "/v1/debug/traces" or \
                path.startswith("/v1/debug/traces/"):
            self._debug_traces(path, query, local)
        elif path == "/-/stats":
            # fleet-internal: this acceptor's raw metric values as JSON
            # (the peer merging /metrics sums these; JSON beats parsing
            # prometheus text back apart)
            self._send_json(200, {"values": d.metrics_values()})
        elif path == "/v1/traces":
            self._send_json(200, {"traces": d.registry.names()})
        elif path.startswith("/v1/jobs/"):
            if not d.is_primary:
                self._proxy_to_primary("GET", path, None)
                return
            job = d.jobs.get(path.rsplit("/", 1)[1])
            if job is None:
                self._send_json(404, {
                    "error": "unknown_job",
                    "detail": f"no such job {path.rsplit('/', 1)[1]!r}",
                })
            else:
                self._send_json(200, job.to_doc())
        else:
            self._send_json(404, {
                "error": "unknown_route", "detail": f"no route {path!r}",
            })

    def do_POST(self) -> None:  # noqa: N802 - stdlib signature
        d = self.daemon_obj
        path = self.path.split("?", 1)[0].rstrip("/")
        if path.startswith("/v1/cluster/"):
            # joins + 1 Hz heartbeats are cluster-internal control
            # traffic: counting them would make a clustered node's
            # request counters diverge from a single node serving the
            # same user load
            self._cluster_post(path)
            return
        d._count("serve_requests_total")
        self._track(_post_route(path))
        if path == "/v1/simulate":
            d._count("serve_requests_simulate_total")
            body = self._read_body()
            if body is None:
                return
            # serve v3 hot path: a request whose exact response bytes
            # are already published in the shared mmap tier is answered
            # HERE — no admission slot, no dispatch, no re-pricing, no
            # serialization.  The stored body is the final envelope a
            # warm priced request would produce (cache_hit true), so
            # clients cannot tell the tiers apart except by latency.
            # deadline_ms is stripped from the hot key (volatile), so
            # a MALFORMED one must be rejected before the hot lookup —
            # the cold path 400s it, and the tiers must be
            # indistinguishable except by latency
            deadline_ok = True
            if body.get("deadline_ms") is not None:
                try:
                    float(body["deadline_ms"])
                except (TypeError, ValueError):
                    deadline_ok = False
            tr = self._trace
            t_hot = time.monotonic() if tr is not None else 0.0
            hot_key = (
                d.hot_key_for("simulate", body) if deadline_ok else None
            )
            blob = (
                d.hot.get(hot_key)
                if hot_key is not None and not d.admission.draining
                else None
            )
            if tr is not None:
                # one span covers key derivation + the mmap lookup —
                # the whole of what a hot hit pays
                tr.add_span(
                    "hot_lookup", t_hot, time.monotonic() - t_hot,
                )
            if blob is not None:
                # serve_hot_hits_total rides /metrics from the hot
                # store's own counters — not double-counted here
                if tr is not None:
                    tr.meta["tier"] = "hot"
                self._send_body(200, blob)
                return
            # cluster trace affinity, AFTER the hot miss: a local hot
            # hit is byte-identical wherever it is served, but a miss
            # belongs at the key's owner, where the hot/compiled state
            # for this trace concentrates
            if deadline_ok and self._maybe_forward("simulate", path, body):
                return
            self._run_sync(
                "simulate", d.worker.simulate, body=body, hot_key=hot_key,
            )
        elif path == "/v1/lint":
            d._count("serve_requests_lint_total")
            body = self._read_body()
            if body is None:
                return
            if self._maybe_forward("lint", path, body):
                return
            self._run_sync("lint", d.worker.lint, body=body)
        elif path in ("/v1/sweep", "/v1/campaign", "/v1/advise",
                      "/v1/fleet"):
            kind = path.rsplit("/", 1)[1]
            if d.is_primary:
                # secondaries skip the per-kind counter: the primary
                # counts the forwarded copy, and fleet metrics sum
                d._count(f"serve_requests_{kind}_total")
            if not d.is_primary:
                # serve v3: exactly one acceptor (the primary) owns the
                # JobTable — async job ids, persistence, and restart
                # recovery stay single-writer.  Secondaries forward the
                # raw request over loopback to its direct listener.
                # The size cap applies BEFORE the body is read, exactly
                # like the local path's _read_body (including its 411
                # on an unparseable length).
                try:
                    length = int(
                        self.headers.get("Content-Length", "0") or 0
                    )
                except ValueError:
                    self._send_json(411, {
                        "error": "length_required",
                        "detail": "Content-Length is required",
                    })
                    return
                if length > d.max_request_bytes:
                    self.close_connection = True
                    self._send_json(413, {
                        "error": "request_too_large",
                        "detail": (
                            f"body is {length} bytes; this server caps "
                            f"requests at {d.max_request_bytes}"
                        ),
                    }, headers={"Connection": "close"})
                    return
                raw = self.rfile.read(length) if length > 0 else b""
                self._proxy_to_primary("POST", path, raw)
                return
            body = self._read_body()
            if body is None:
                return
            try:
                job = d.jobs.submit(kind, body)
            except Overloaded as e:
                d._count("serve_rejected_429_total")
                self._send_json(429, {
                    "error": "overloaded",
                    "detail": "job queue full; retry later",
                }, headers={"Retry-After": int(e.retry_after_s)})
                return
            except Draining:
                d._count("serve_draining_503_total")
                self._send_json(503, {
                    "error": "draining",
                    "detail": "server is draining; not accepting jobs",
                })
                return
            self._send_json(202, {
                "job_id": job.job_id, "status": job.status,
                "poll": f"/v1/jobs/{job.job_id}",
            })
        else:
            self._send_json(404, {
                "error": "unknown_route", "detail": f"no route {path!r}",
            })

    def do_DELETE(self) -> None:  # noqa: N802 - stdlib signature
        """``DELETE /v1/jobs/<id>`` — cooperative job cancellation
        (tpusim.guard): a queued job lands terminal ``cancelled``
        immediately; a running campaign/advise job has its token
        tripped and unwinds at its next scenario/cell boundary with
        everything completed already journaled (a later ``--resume``
        re-prices nothing)."""
        d = self.daemon_obj
        d._count("serve_requests_total")
        path = self.path.split("?", 1)[0].rstrip("/")
        self._track("jobs" if path.startswith("/v1/jobs/") else "other")
        if not path.startswith("/v1/jobs/"):
            self._send_json(404, {
                "error": "unknown_route", "detail": f"no route {path!r}",
            })
            return
        if not d.is_primary:
            self._proxy_to_primary("DELETE", path, None)
            return
        job_id = path.rsplit("/", 1)[1]
        status = d.jobs.cancel(job_id)
        if status is None:
            self._send_json(404, {
                "error": "unknown_job",
                "detail": f"no such job {job_id!r}",
            })
            return
        if status in ("cancelled", "cancelling"):
            d._count("serve_jobs_cancel_requests_total")
        self._send_json(200, {"job_id": job_id, "status": status})

    def _debug_traces(self, path: str, query: str, local: bool) -> None:
        """``GET /v1/debug/traces`` (summaries, slowest first, fleet-
        merged) and ``/v1/debug/traces/<id>`` (one span tree; add
        ``?format=chrome`` for the Perfetto/Chrome export).  404 when
        tracing is off — the debug surface only exists when the flight
        recorder does."""
        d = self.daemon_obj
        rt = d.reqtrace
        if rt is None:
            self._send_json(404, {
                "error": "tracing_disabled",
                "detail": (
                    "start the daemon with --trace-requests to record "
                    "request traces"
                ),
            })
            return
        if path == "/v1/debug/traces":
            docs = rt.traces_doc()
            if d.in_fleet and not local:
                docs = d.fleet_traces_doc(docs)
            self._send_json(200, {"traces": docs})
            return
        trace_id = path.rsplit("/", 1)[1]
        doc = rt.get(trace_id)
        if doc is None and d.in_fleet and not local:
            doc = d.fleet_trace_get(trace_id)
        if doc is None:
            self._send_json(404, {
                "error": "unknown_trace",
                "detail": f"no recorded trace {trace_id!r}",
            })
            return
        if "format=chrome" in query:
            from tpusim.obs.export import request_chrome_trace

            # the raw viewer document, no response envelope: this body
            # is meant to be saved and loaded into Perfetto/chrome as-is
            self._send_text(
                200, json.dumps(request_chrome_trace(doc), sort_keys=True),
                "application/json",
            )
            return
        self._send_json(200, {"trace": doc})

    # -- cluster routes (tpusim.serve.cluster) -------------------------------

    def _cluster_get(self, path: str) -> None:
        d = self.daemon_obj
        if path == "/v1/cluster/stats":
            # cluster-internal: this NODE's raw values (acceptor-
            # merged in front mode) — what peer nodes fold into their
            # node-grain /metrics merge
            self._send_json(200, {"values": d.node_stats_values()})
            return
        if path != "/v1/cluster/view":
            self._send_json(404, {
                "error": "unknown_route", "detail": f"no route {path!r}",
            })
            return
        if d.in_fleet and not d.is_primary:
            # the registry (and the member-side gossip cache) live on
            # acceptor 0; secondaries forward like job routes do
            self._proxy_to_primary("GET", path, None, counted=False)
            return
        view = d.cluster_view_doc()
        if view is None:
            self._send_json(404, {
                "error": "no_cluster",
                "detail": (
                    "this node is not part of a cluster (start peers "
                    "with --join pointing here, or --join one)"
                ),
            })
            return
        self._send_json(200, view)

    def _cluster_post(self, path: str) -> None:
        d = self.daemon_obj
        if path not in ("/v1/cluster/join", "/v1/cluster/beat"):
            self._send_json(404, {
                "error": "unknown_route", "detail": f"no route {path!r}",
            })
            return
        if d.in_fleet and not d.is_primary:
            # single-writer epoch: only acceptor 0 mutates membership
            try:
                length = int(
                    self.headers.get("Content-Length", "0") or 0
                )
            except ValueError:
                length = 0
            if length > d.max_request_bytes:
                self.close_connection = True
                self._send_json(413, {
                    "error": "request_too_large",
                    "detail": "cluster control bodies are small",
                }, headers={"Connection": "close"})
                return
            raw = self.rfile.read(length) if length > 0 else b""
            self._proxy_to_primary("POST", path, raw, counted=False)
            return
        body = self._read_body()
        if body is None:
            return
        node_id = str(body.get("node_id") or "")
        if not node_id:
            self._send_json(400, {
                "error": "bad_request", "detail": "node_id is required",
            })
            return
        try:
            epoch = int(body.get("epoch") or 0)
        except (TypeError, ValueError):
            self._send_json(400, {
                "error": "bad_request", "detail": "epoch must be an int",
            })
            return
        if path == "/v1/cluster/join":
            reg = d.ensure_cluster_registry()
            if reg is None:
                # we are a member ourselves — point the joiner at OUR
                # primary instead of forking a second epoch writer
                self._send_json(409, {
                    "error": "not_primary",
                    "detail": (
                        f"this node joined {d.cluster_join}; join the "
                        f"primary there"
                    ),
                })
                return
            try:
                view = reg.join(
                    node_id, str(body.get("url") or ""), epoch,
                )
            except StaleEpoch as e:
                self._send_json(409, {
                    "error": "stale_epoch", "detail": str(e),
                })
                return
        else:
            reg = d.cluster
            if reg is None:
                # beats only make sense against a live registry; a
                # restarted primary lost its table — members must
                # rejoin fresh (409 is exactly that signal)
                self._send_json(409, {
                    "error": "no_cluster",
                    "detail": "no registry here; rejoin with epoch 0",
                })
                return
            try:
                view = reg.beat(
                    node_id, epoch,
                    shedding=bool(body.get("shedding")),
                )
            except StaleEpoch as e:
                self._send_json(409, {
                    "error": "stale_epoch", "detail": str(e),
                })
                return
        self._send_json(200, view)

    def _maybe_forward(self, endpoint: str, path: str, body: dict) -> bool:
        """Cluster trace affinity: when the affinity key's owner is
        another alive node, forward the request there one-hop and relay
        its bytes.  True when the response was sent here."""
        d = self.daemon_obj
        if self.headers.get(FORWARD_HEADER):
            # already forwarded once: serve locally no matter what our
            # ring says — the one-hop guarantee that kills routing
            # loops during view skew
            return False
        target = d.cluster_owner_url(endpoint, body)
        if target is None:
            return False
        return self._forward_to_node(target, path, body, endpoint)

    def _forward_to_node(
        self, url: str, path: str, body: dict, endpoint: str,
    ) -> bool:
        import http.client
        from urllib.parse import urlsplit

        d = self.daemon_obj
        raw = json.dumps(body).encode()
        tr = self._trace
        try:
            u = urlsplit(url)
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=30.0,
            )
            headers = {
                "Content-Type": "application/json",
                FORWARD_HEADER: d.node_id,
            }
            if tr is not None:
                headers[TRACE_HEADER] = tr.trace_id
            conn.request("POST", path, body=raw, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
        except (OSError, http.client.HTTPException):
            # the owner is unreachable (dying, not yet reaped): serve
            # locally — pricing is node-invariant, only cache locality
            # suffers, and a request must never fail because the ring
            # is mid-heal
            d._count("cluster_forward_fallback_total")
            return False
        d._count("cluster_forwarded_total")
        # the owner counted the forwarded copy as ITS request;
        # compensate ours so node-summed totals count each user
        # request exactly once (the _proxy_to_primary discipline)
        d._count("serve_requests_total", -1.0)
        d._count(f"serve_requests_{endpoint}_total", -1.0)
        self._trace = None
        self._route = None
        if tr is not None:
            self._relay_tid = resp.getheader(TRACE_HEADER) or tr.trace_id
        self._send_body(resp.status, payload)
        return True

    def _proxy_to_primary(
        self, method: str, path: str, raw, counted: bool = True,
    ) -> None:
        """Forward one job-family request to the primary acceptor's
        direct listener (serve v3: the JobTable is single-owner).  The
        primary's response travels back verbatim.  ``counted=False``
        for cluster control routes, which never touched the request
        counters."""
        import http.client

        d = self.daemon_obj
        # the request was already counted at route entry, and the
        # primary will count the forwarded copy when it handles it —
        # without this compensation every proxied job request would
        # show as TWO requests in the fleet-summed /metrics
        if counted:
            d._count("serve_requests_total", -1.0)
        # the same rule governs tracing: drop this acceptor's trace
        # (never observed/recorded — the fleet histogram counts must
        # keep summing to serve_requests_total) and propagate its ID
        # over the hop so the PRIMARY records the span tree under it
        tr = self._trace
        self._trace = None
        self._route = None
        target = d.primary_direct
        if target is None:
            d._count("serve_proxy_unavailable_total")
            self._send_json(503, {
                "error": "primary_unavailable",
                "detail": (
                    "the primary acceptor (job owner) is restarting; "
                    "retry shortly"
                ),
            }, headers={"Retry-After": 1})
            return
        try:
            conn = http.client.HTTPConnection(d.host, target, timeout=30.0)
            headers = {"Accept": "application/json"}
            if raw:
                headers["Content-Type"] = "application/json"
            if tr is not None:
                headers[TRACE_HEADER] = tr.trace_id
            conn.request(method, path, body=raw or None, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            if tr is not None:
                # relay the primary's (== our pinned) trace ID on the
                # response we forward back to the client
                self._relay_tid = (
                    resp.getheader(TRACE_HEADER) or tr.trace_id
                )
            conn.close()
        except (OSError, http.client.HTTPException):
            d._count("serve_proxy_unavailable_total")
            self._send_json(503, {
                "error": "primary_unavailable",
                "detail": (
                    "the primary acceptor (job owner) did not answer; "
                    "retry shortly"
                ),
            }, headers={"Retry-After": 1})
            return
        d._count("serve_proxied_total")
        self._send_body(resp.status, payload)

    def _run_sync(
        self, endpoint: str, fn, body: dict | None = None,
        hot_key: str | None = None,
    ) -> None:
        """Admission-gated execution of one synchronous endpoint."""
        d = self.daemon_obj
        if body is None:
            body = self._read_body()
        if body is None:
            return
        budget_s = d.deadline_s
        if body.get("deadline_ms") is not None:
            try:
                budget_s = float(body["deadline_ms"]) / 1000.0
            except (TypeError, ValueError):
                self._send_json(400, {
                    "error": "bad_request",
                    "detail": "deadline_ms must be a number",
                })
                return
        budget_s = min(max(budget_s, 0.0), MAX_DEADLINE_S)
        deadline = time.monotonic() + budget_s
        if d.watchdog is not None and d.watchdog.shedding:
            # the memory ladder's terminal step: past the hard RSS
            # threshold with every droppable store already dropped,
            # admitting more work would invite the OOM-killer — shed
            # with a hint sized to the sampler's recovery cadence
            d._count("guard_shed_503_total")
            self._send_json(503, {
                "error": "memory_pressure",
                "detail": (
                    "daemon is over its --max-rss hard threshold and "
                    "shedding load; retry shortly"
                ),
            }, headers={"Retry-After": 2})
            return
        if d.cluster_shed():
            # the watchdog ladder at node grain: too few alive nodes
            # to absorb this load — shed instead of queueing work the
            # survivors will only time out on
            d._count("cluster_shed_total")
            self._send_json(503, {
                "error": "cluster_degraded",
                "detail": (
                    "alive cluster nodes are below the configured "
                    "floor; retry after the fleet heals"
                ),
            }, headers={"Retry-After": 2})
            return
        tr = self._trace
        try:
            t_adm = time.monotonic()
            with d.admission.admit(deadline):
                if tr is not None:
                    # the admission span is the queue wait: admit()
                    # blocks in __enter__ until a slot frees
                    tr.add_span(
                        "admission", t_adm, time.monotonic() - t_adm,
                    )
                if d.work_hook is not None:
                    d.work_hook(endpoint, body)
                if time.monotonic() >= deadline:
                    raise DeadlineExceeded("deadline expired at admission")
                if tr is None:
                    result = d.execute_sync(endpoint, fn, body, deadline)
                else:
                    t_disp = time.monotonic()
                    try:
                        result = d.execute_sync(
                            endpoint, fn, body, deadline, reqtrace=tr,
                        )
                    finally:
                        # recorded on the way out even for the 504/422
                        # ladder below — those are the traces the
                        # recorder's error ring exists for
                        tr.add_span(
                            "dispatch", t_disp,
                            time.monotonic() - t_disp,
                        )
        except RequestError as e:
            if e.status == 400:
                d._count("serve_validation_400_total")
            self._send_json(e.status, {
                "error": e.code, "detail": e.detail, **e.extra,
            })
            return
        except Overloaded as e:
            d._count("serve_rejected_429_total")
            self._send_json(429, {
                "error": "overloaded",
                "detail": (
                    f"{d.admission.max_inflight} in flight and the wait "
                    f"queue is full; retry later"
                ),
            }, headers={"Retry-After": int(e.retry_after_s)})
            return
        except Degraded as e:
            # serve v2 load shedding: the worker pool is below its live
            # floor, so queueing would only convert this request into a
            # slow 504 — tell the client when the restart backoff opens.
            # serve_shed_503_total is minted by the supervisor alone
            # (its stats_dict merges last into /metrics); counting here
            # too would shadow-write a value the merge then overwrites.
            self._send_json(503, {
                "error": "degraded",
                "detail": (
                    "worker pool is below its live floor; retry after "
                    "the restart backoff"
                ),
            }, headers={"Retry-After": int(e.retry_after_s)})
            return
        except (CooperativeCancel, OperationCancelled):
            # tpusim.guard: the deadline tripped INSIDE the pricing
            # stack and the run cancelled in-process — still a 504, but
            # the worker (process or thread) survives with its caches
            # warm and zero restarts.  Ordered before WorkerTimeout/
            # DeadlineExceeded: CooperativeCancel subclasses them.
            d._count("serve_deadline_504_total")
            d._count("guard_coop_504_total")
            self._send_json(504, {
                "error": "deadline_exceeded",
                "detail": (
                    f"pricing exceeded the {budget_s:.3f}s deadline and "
                    f"was cancelled in-process (cooperative cancel); "
                    f"the worker survives with warm caches"
                ),
            })
            return
        except WorkerTimeout:
            # ordered before DeadlineExceeded (its parent): the request
            # STARTED and its worker was killed for outliving the budget
            d._count("serve_deadline_504_total")
            self._send_json(504, {
                "error": "deadline_exceeded",
                "detail": (
                    f"pricing exceeded the {budget_s:.3f}s deadline; "
                    f"the worker was killed and is being restarted"
                ),
            })
            return
        except DeadlineExceeded:
            d._count("serve_deadline_504_total")
            self._send_json(504, {
                "error": "deadline_exceeded",
                "detail": (
                    f"request did not start within its "
                    f"{budget_s:.3f}s deadline"
                ),
            })
            return
        except Draining:
            d._count("serve_draining_503_total")
            self._send_json(503, {
                "error": "draining",
                "detail": "server is draining; retry against a peer",
            })
            return
        except Exception as e:  # noqa: BLE001 - the 500 boundary
            d._count("serve_errors_total")
            self._send_json(500, {
                "error": "internal",
                "detail": f"{type(e).__name__}: {e}",
            })
            return
        if isinstance(result, (bytes, bytearray)):
            self._send_body(200, bytes(result))
        else:
            self._send_json(200, result)
        if hot_key is not None:
            # publish AFTER answering: the requester never waits on the
            # (one-time) warm-form serialization + fsync'd append
            d.hot_publish(hot_key, result)


class ServeDaemon:
    """Composes the four layers and owns the listener + job threads."""

    def __init__(
        self,
        trace_root=None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_inflight: int = 4,
        queue_depth: int = 16,
        deadline_s: float = 30.0,
        max_request_bytes: int = DEFAULT_MAX_REQUEST_BYTES,
        result_cache=None,
        cache_entries: int = 4096,
        workers: int = 1,
        serve_workers: int = 0,
        min_workers: int = 1,
        restart_backoff_s: float = 0.05,
        chaos_hooks: bool = False,
        job_workers: int = 1,
        job_queue_depth: int = 16,
        drain_grace_s: float = 60.0,
        state_dir=None,
        verbose: bool = False,
        work_hook=None,
        cache_quota=None,
        max_rss=None,
        max_worker_rss=None,
        compile_cache=None,
        hot_cache=None,
        hot_quota_bytes=None,
        strict_lint: bool = False,
        acceptor_index: int | None = None,
        acceptors_total: int = 0,
        reuse_port: bool = False,
        public_listener: bool = True,
        quarantine_dir=None,
        close_fds=(),
        worker_close_fds=(),
        trace_requests: bool = False,
        access_log=None,
        cluster_join: str | None = None,
        cluster_beat_s: float | None = None,
        cluster_missed_beats: int | None = None,
        cluster_min_nodes: int = 1,
    ):
        from pathlib import Path

        from tpusim.guard.store import parse_size
        from tpusim.perf.cache import ResultCache, as_result_cache

        self.host = host
        self._requested_port = int(port)
        self.deadline_s = float(deadline_s)
        self.max_request_bytes = int(max_request_bytes)
        self.drain_grace_s = float(drain_grace_s)
        self.verbose = bool(verbose)
        self.work_hook = work_hook
        # serve v3 front-tier identity: None = standalone daemon (the
        # PR 5/9 topologies, unchanged); an int = this process is
        # acceptor <index> of a FrontSupervisor fleet.  Acceptor 0 is
        # the primary (sole owner of the async JobTable); the rest
        # proxy job-family routes to its direct listener.
        self.acceptor_index = acceptor_index
        self.acceptors_total = max(int(acceptors_total), 0)
        self.in_fleet = acceptor_index is not None
        self.is_primary = acceptor_index in (None, 0)
        self.reuse_port = bool(reuse_port)
        self.public_listener = bool(public_listener)
        self._close_fds = tuple(close_fds or ())
        # fds this daemon needs but its forked WORKERS must close (an
        # acceptor's control pipe and fd-passing socket: a worker
        # holding them open would keep a dead acceptor's channels
        # half-alive — the front parent would ship connections into a
        # socketpair nobody drains)
        self._worker_close_fds = tuple(worker_close_fds or ())
        # peer map (acceptor index -> direct port), pushed by the front
        # supervisor after boot and on membership changes
        self._peers: dict[int, int] = {}
        self.primary_direct: int | None = None
        self._peer_lock = threading.Lock()
        # multi-node cluster (tpusim.serve.cluster): --join makes this
        # daemon a member heartbeating a remote primary; a daemon
        # started WITHOUT --join becomes a cluster primary lazily, on
        # the first /v1/cluster/join it receives.  Until either
        # happens the daemon carries zero cluster state and mints zero
        # cluster stats keys — the single-node path stays key- and
        # byte-identical by construction.
        self.cluster_join = cluster_join or None
        self.cluster_beat_s = float(
            cluster_beat_s if cluster_beat_s is not None
            else DEFAULT_BEAT_INTERVAL_S
        )
        self.cluster_missed_beats = int(
            cluster_missed_beats if cluster_missed_beats is not None
            else DEFAULT_MISSED_BEATS
        )
        self.cluster_min_nodes = max(int(cluster_min_nodes), 1)
        self.cluster = None            # ClusterRegistry (primary side)
        self.cluster_node_id = None    # stamped once clustered
        self._cluster_view = None      # gossiped view (member side)
        self._cluster_lock = threading.Lock()
        self._heartbeat = None
        self._reaper: threading.Thread | None = None
        self._stop_cluster = threading.Event()

        # the process-wide shared result cache: always at least the
        # in-memory tier (sharing across requests IS the service's
        # reason to exist); --result-cache adds the disk tier
        self.result_cache = as_result_cache(result_cache) or ResultCache(
            max_entries=cache_entries
        )
        self.result_cache.max_entries = max(
            self.result_cache.max_entries, int(cache_entries)
        )
        # tpusim.guard: --cache-quota bounds the shared disk tier.  The
        # daemon's own publishes GC it; worker fleets get the same quota
        # via settings so every writer of the dir enforces it.
        self.cache_quota_bytes = parse_size(cache_quota)
        if self.cache_quota_bytes is not None:
            self.result_cache.quota_bytes = self.cache_quota_bytes
        # tpusim.fastpath.store: --compile-cache mounts the durable
        # compiled-module tier process-wide BEFORE the registry exists,
        # so every trace the registry loads defers its parse — a cold
        # first request against a warm store prices from mmapped
        # columns with zero Python IR construction.  Durable writes
        # (fsync-before-replace): this tier serves a fleet, and a
        # daemon killed mid-publish must never leave a short-read
        # record for its successors to warn about.
        self.compile_store = None
        if compile_cache is not None and compile_cache is not False:
            from tpusim.fastpath.store import as_compile_store

            self.compile_store = as_compile_store(
                compile_cache, durable=True,
                quota_bytes=self.cache_quota_bytes,
            )
        self.registry = TraceRegistry(trace_root)
        # --strict-lint: every simulate request passes the trace-level
        # lint gate first — errors OR warnings refuse with 422 + the
        # diagnostics doc, verdict cached by content hash so the fleet
        # lints each distinct trace once
        self.strict_lint = bool(strict_lint)
        self.worker = ServeWorker(
            self.registry, result_cache=self.result_cache, workers=workers,
            strict_lint=self.strict_lint,
        )
        # serve v3: the shared mmap hot-response cache.  Keyed by the
        # supervisor's content-hash affinity identity + a per-trace
        # stat fingerprint; generation-stamped with model_version /
        # format version / tuned-overlay state so staleness is
        # structurally impossible (a bump orphans the files).
        self.hot = None
        if hot_cache:
            from tpusim.serve.hotcache import (
                HotResponseCache, hot_generation,
            )

            hot_dir = (
                hot_cache if isinstance(hot_cache, (str, Path))
                else ".tpusim_hot"  # the --result-cache default idiom
            )
            self.hot = HotResponseCache(
                hot_dir,
                generation=hot_generation(
                    self.worker.model_version, SERVE_FORMAT_VERSION,
                ),
                **(
                    {"quota_bytes": int(hot_quota_bytes)}
                    if hot_quota_bytes else {}
                ),
            )
        self._trace_fp_cache: dict[str, str] = {}
        self._trace_fp_lock = threading.Lock()
        # serve v2: serve_workers >= 1 mounts the supervised pre-forked
        # worker pool — sync pricing (simulate/lint) moves into N
        # crash-isolated processes behind the admission layer, each with
        # its own registry + L1 cache and the daemon's disk cache dir
        # (when mounted) as the shared durable L2.  0 keeps the PR 5
        # single-process path, byte-identical by contract.
        self.serve_workers = max(int(serve_workers), 0)
        self.supervisor: Supervisor | None = None
        if self.serve_workers > 0:
            self.supervisor = Supervisor(
                settings={
                    "trace_root": str(trace_root) if trace_root else None,
                    "disk_cache_dir": (
                        str(self.result_cache.disk_dir)
                        if self.result_cache.disk_dir else None
                    ),
                    "cache_entries": int(cache_entries),
                    "cache_quota_bytes": self.cache_quota_bytes,
                    "compile_cache_dir": (
                        str(self.compile_store.disk_dir)
                        if self.compile_store is not None else None
                    ),
                    "chaos_hooks": bool(chaos_hooks),
                    "strict_lint": self.strict_lint,
                    # lets workers serialize the FINAL response body
                    # (byte-identical to _send_json's by construction)
                    "format_version": SERVE_FORMAT_VERSION,
                },
                num_workers=self.serve_workers,
                min_live=min_workers,
                restart_backoff_s=restart_backoff_s,
                max_worker_rss_bytes=parse_size(max_worker_rss),
                # serve v3: a shared quarantine dir makes poison
                # refusal fleet-wide across acceptors
                quarantine_dir=quarantine_dir,
            )
            if self.result_cache.disk_dir is not None:
                # the parent still publishes to the shared dir (async
                # sweep/campaign/advise jobs price in parent threads);
                # its writes must carry the same fsync-before-replace
                # guarantee the workers' durable L2 does, or a host
                # crash mid-parent-publish leaves the short-read record
                # the durable tier exists to rule out
                self.result_cache.durable = True
        self.admission = AdmissionController(
            max_inflight=max_inflight, queue_depth=queue_depth,
        )
        # --state-dir makes accepted jobs crash-safe: specs persist
        # under <state_dir>/jobs (re-enqueued on restart) and campaign
        # jobs journal per-scenario progress under <state_dir>/campaigns
        # so a restarted daemon RESUMES them instead of re-pricing
        self.state_dir = Path(state_dir) if state_dir else None
        self.jobs = JobTable(
            queue_depth=job_queue_depth,
            persist_dir=(
                self.state_dir / "jobs" if self.state_dir else None
            ),
            # reclaim per-job campaign journals when the job ages out
            # of retention — journals are scenario-grained and fsync'd,
            # so a long-running daemon would otherwise grow disk
            # monotonically with every campaign ever run
            evict_hook=self._evict_job_state,
        )

        # tpusim.guard: --max-rss mounts the memory watchdog with the
        # documented degradation ladder (shrink LRUs → drop compiled
        # tier → force lean streaming); its terminal shed state makes
        # _run_sync answer 503 + Retry-After instead of letting the
        # OOM-killer choose a victim
        self.watchdog = None
        max_rss_bytes = parse_size(max_rss)
        if max_rss_bytes is not None:
            from tpusim.guard.watchdog import MemoryWatchdog, default_ladder

            self.watchdog = default_ladder(
                MemoryWatchdog(
                    soft_bytes=None, hard_bytes=max_rss_bytes,
                ),
                result_cache=self.result_cache,
            )
        #: startup integrity-sweep counters (guard_* /metrics gauges)
        self._guard_startup: dict[str, float] = {}

        self._httpd: ThreadingHTTPServer | None = None
        self._direct_httpd: ThreadingHTTPServer | None = None
        self._direct_thread: threading.Thread | None = None
        self._serve_thread: threading.Thread | None = None
        self._job_threads: list[threading.Thread] = []
        # 0 is a legitimate (test-facing) value: accept + persist jobs
        # without draining them — the restart-recovery path in a box
        self._job_workers = max(int(job_workers), 0)
        self._stop_jobs = threading.Event()
        self._stopped = threading.Event()
        self._counters: dict[str, float] = {}
        self._counter_lock = threading.Lock()
        self._clock0 = time.monotonic()

        # request-scoped tracing (L24, tpusim.obs.reqtrace): OFF by
        # default — None means the handler pays one attribute test per
        # request, zero new stats keys, byte-identical responses
        self.reqtrace = None
        if trace_requests:
            from tpusim.obs.reqtrace import RequestTracer

            self.reqtrace = RequestTracer(acceptor_index=acceptor_index)
        # structured JSONL access log (independent of tracing; lines
        # carry trace IDs only when tracing is also on)
        self.access_log = None
        if access_log:
            from tpusim.obs.reqtrace import AccessLog

            log_path = (
                Path(access_log) if isinstance(access_log, (str, Path))
                else (
                    self.state_dir / "access.jsonl"
                    if self.state_dir else Path("tpusim-access.jsonl")
                )
            )
            if self.in_fleet:
                # one file per acceptor: concurrent writers rotating one
                # shared file would race each other's os.replace
                log_path = log_path.with_name(
                    f"{log_path.stem}.{acceptor_index}{log_path.suffix}"
                )
            self.access_log = AccessLog(log_path)
        # fd-passing front mode: accept timestamps for in-flight handed
        # connections, keyed by socket identity until the first request
        # on each connection claims its fd_dispatch span
        self._accept_ts: dict[int, tuple[float, float]] = {}
        self._accept_lock = threading.Lock()

    # -- counters ------------------------------------------------------------

    def _count(self, key: str, delta: float = 1.0) -> None:
        with self._counter_lock:
            self._counters[key] = self._counters.get(key, 0.0) + delta

    def _count_status(self, status: int) -> None:
        bucket = (
            "serve_responses_ok_total" if status < 400 else
            "serve_responses_client_error_total" if status < 500 else
            "serve_responses_server_error_total"
        )
        self._count(bucket)

    def metrics_values(self) -> dict[str, float]:
        """This process's raw metric values — the assembly half of
        ``/metrics``, also served as JSON on the fleet-internal
        ``/-/stats`` route so peer acceptors can merge without parsing
        Prometheus text back apart."""
        with self._counter_lock:
            values = dict(self._counters)
        values["serve_uptime_s"] = time.monotonic() - self._clock0
        if self.hot is not None:
            for k, v in self.hot.stats_dict().items():
                values[f"serve_{k}"] = v
        for k, v in self.admission.stats_dict().items():
            values[f"serve_admission_{k}"] = v
        for k, v in self.jobs.stats_dict().items():
            values[f"serve_{k}"] = v
        for k, v in self.registry.stats_dict().items():
            values[f"serve_registry_{k}"] = v
        for k, v in self.worker.stats_dict().items():
            values[f"serve_{k}"] = v
        if self.supervisor is not None:
            for k, v in self.supervisor.stats_dict().items():
                values[f"serve_{k}"] = v
        # compile-cache effectiveness — only when the durable compiled
        # tier is mounted (the faults_* discipline on /metrics too)
        from tpusim.fastpath.store import get_compile_store

        if get_compile_store() is not None:
            from tpusim.perf.cache import compiled_cache_stats

            for k, v in compiled_cache_stats().items():
                values[f"fastpath_{k}"] = v
        # tpusim.guard gauges — only when guard features are active
        # (quota / watchdog / startup sweep), mirroring the report-key
        # discipline: an un-governed daemon's scrape is unchanged
        if (
            self.result_cache.quota_bytes is not None
            or self.result_cache.quota_entries is not None
        ):
            for k, v in self.result_cache.guard_stats_dict().items():
                values[f"guard_{k}"] = v
        if self.watchdog is not None:
            for k, v in self.watchdog.stats_dict().items():
                values[f"guard_{k}"] = v
        for k, v in self._guard_startup.items():
            values[f"guard_{k}"] = v
        # request-trace histograms + recorder counters — ONLY when
        # tracing is active (the guard_* discipline on /metrics: a
        # tracing-off daemon's scrape and /-/stats are key-identical)
        if self.reqtrace is not None:
            values.update(self.reqtrace.metrics_values())
        # cluster membership counters — registry-only (the single
        # epoch writer is also their single stats writer); a
        # never-clustered daemon's scrape stays key-identical
        if self.cluster is not None:
            values.update(self.cluster.stats_dict())
        return values

    @staticmethod
    def _render_metrics(values: dict[str, float]) -> str:
        from tpusim.obs.export import prometheus_text
        from tpusim.obs.reqtrace import histogram_exposition

        # split the (possibly fleet-merged) latency-histogram state out
        # first: its keys render as real histogram-typed series, and
        # everything else stays on the hardened gauge/counter path
        rest, hist_lines = histogram_exposition(values)
        text = prometheus_text(
            rest,
            help_text={
                "serve_requests_total": "HTTP requests received",
                "serve_uptime_s": "seconds since daemon start",
            },
        )
        if hist_lines:
            text += "\n".join(hist_lines) + "\n"
        return text

    def metrics_text(self) -> str:
        """The ``/metrics`` document — every serve counter plus the
        admission/job/registry/cache gauges, in Prometheus exposition
        format via the hardened :func:`~tpusim.obs.export.
        prometheus_text`."""
        return self._render_metrics(self.metrics_values())

    # -- fleet views (serve v3) ----------------------------------------------

    def set_peers(
        self, peers: dict[int, int], primary_direct: int | None,
    ) -> None:
        """Membership push from the front supervisor: acceptor index →
        direct port, plus the primary's direct port (job proxy target)."""
        with self._peer_lock:
            self._peers = {int(k): int(v) for k, v in peers.items()}
            self.primary_direct = primary_direct

    def _peer_ports(self) -> list[tuple[int, int]]:
        with self._peer_lock:
            return sorted(
                (i, p) for i, p in self._peers.items()
                if i != self.acceptor_index
            )

    def _fetch_peer_json(self, port: int, path: str) -> dict | None:
        import http.client
        import json as _json

        try:
            # sub-second timeout: a health probe must not stack peer
            # waits past a balancer's own check timeout
            conn = http.client.HTTPConnection(self.host, port, timeout=0.8)
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != 200:
                return None
            return _json.loads(payload)
        except (OSError, ValueError, http.client.HTTPException):
            return None

    def _fetch_peers_json(self, path: str) -> dict[int, dict | None]:
        """All peers' ``path`` docs, fetched CONCURRENTLY — N-1
        sequential timeouts against down peers would turn a partial
        outage into a failed health check on the healthy acceptors."""
        peers = self._peer_ports()
        results: dict[int, dict | None] = {}
        if not peers:
            return results

        def fetch(idx, port):
            results[idx] = self._fetch_peer_json(port, path)

        threads = [
            threading.Thread(target=fetch, args=(i, p), daemon=True)
            for i, p in peers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=2.0)
        return {i: results.get(i) for i, _p in peers}

    #: fleet-merge keys that describe ONE shared resource (the hot
    #: store every acceptor mounts): summing N identical views would
    #: report N× the real state, so these take the max instead
    _FLEET_MAX_KEYS = frozenset({
        "serve_uptime_s", "serve_hot_entries", "serve_hot_segment_bytes",
    })

    @classmethod
    def _merge_values(cls, merged: dict, vals: dict) -> None:
        """Fold one peer's raw values into ``merged`` — counters sum,
        the shared-resource gauges take the max."""
        for k, v in vals.items():
            if not isinstance(v, (int, float)):
                continue
            if k in cls._FLEET_MAX_KEYS:
                merged[k] = max(merged.get(k, 0.0), v)
            else:
                merged[k] = merged.get(k, 0.0) + v

    def _merged_acceptor_values(self) -> tuple[dict, int]:
        """This NODE's values: local plus every live peer acceptor's,
        merged.  Returns ``(values, acceptors_alive)``."""
        merged = self.metrics_values()
        alive = 1
        for _idx, doc in self._fetch_peers_json("/-/stats").items():
            vals = (doc or {}).get("values")
            if not isinstance(vals, dict):
                continue
            alive += 1
            self._merge_values(merged, vals)
        return merged, alive

    def fleet_metrics_text(self) -> str:
        """One fleet view: every live acceptor's values merged —
        counters/gauges sum (an N-acceptor fleet's inflight capacity IS
        the sum of its admission bounds), while uptime and the shared
        hot-store gauges take the max, and ``serve_acceptors_alive`` /
        ``_configured`` describe the fleet."""
        merged, alive = self._merged_acceptor_values()
        merged["serve_acceptors_alive"] = alive
        merged["serve_acceptors_configured"] = self.acceptors_total
        return self._render_metrics(merged)

    def local_healthz(self) -> dict:
        doc = {
            "status": "ok",
            "uptime_s": round(time.monotonic() - self._clock0, 3),
            **{f"admission_{k}": v
               for k, v in self.admission.stats_dict().items()},
        }
        if self.in_fleet:
            import os as _os

            doc["acceptor_index"] = self.acceptor_index
            doc["pid"] = _os.getpid()
            doc["direct_port"] = self.direct_port
            doc["primary"] = self.is_primary
        sup = self.supervisor
        if sup is not None:
            alive = sup.alive_count()
            # degraded is a STATE, not an outage: the daemon still
            # answers (shedding), so /healthz stays 200 and balancers
            # read the field, not the status code
            if alive < sup.min_live:
                doc["status"] = "degraded"
            doc["workers_alive"] = alive
            doc["workers_configured"] = sup.num_workers
            doc["workers"] = sup.worker_docs()
        return doc

    def fleet_healthz(self) -> dict:
        """The merged ``/healthz``: this acceptor's local doc plus every
        peer's (over their direct listeners), with one fleet verdict —
        ``ok`` only when every configured acceptor answered ok."""
        local = self.local_healthz()
        acceptors = [local]
        alive = 1
        status = local["status"]
        ports = dict(self._peer_ports())
        for idx, peer in self._fetch_peers_json(
            "/healthz?scope=local"
        ).items():
            if peer is None:
                acceptors.append({
                    "acceptor_index": idx, "status": "unreachable",
                    "direct_port": ports.get(idx),
                })
                status = "degraded"
                continue
            alive += 1
            acceptors.append(peer)
            if peer.get("status") != "ok":
                status = "degraded"
        if self.acceptors_total and alive < self.acceptors_total:
            status = "degraded"
        return {
            "status": status,
            "acceptors_alive": alive,
            "acceptors_configured": self.acceptors_total,
            "acceptors": sorted(
                acceptors, key=lambda a: a.get("acceptor_index", -1)
            ),
        }

    # -- multi-node cluster (tpusim.serve.cluster) ---------------------------

    @property
    def node_id(self) -> str:
        """Cluster identity of this node: its public address.  Stable
        across acceptor restarts (the fleet shares one public port) and
        unique per box+port, which is all membership needs."""
        return f"{self.host}:{self.port}"

    def cluster_active(self) -> bool:
        """True once this daemon is part of a cluster — as the lazy
        primary (registry materialized) or as a joined member (a
        gossiped view arrived)."""
        return self.cluster is not None or self._cluster_view is not None

    def cluster_view_doc(self) -> dict | None:
        """The current membership view: authoritative on the primary,
        the latest gossiped copy on a member, None unclustered."""
        if self.cluster is not None:
            return self.cluster.view()
        return self._cluster_view

    def _on_cluster_view(self, view: dict) -> None:
        self._cluster_view = view

    def ensure_cluster_registry(self):
        """Materialize the primary-side registry on first join (None on
        a member — it can never own the epoch).  Lazy on purpose: a
        daemon nobody joins runs the exact single-node code paths and
        mints zero cluster stats keys."""
        if self.cluster_join is not None:
            return None
        with self._cluster_lock:
            if self.cluster is None:
                from tpusim.serve.cluster import ClusterRegistry

                self.cluster = ClusterRegistry(
                    self.node_id, self.url,
                    beat_interval_s=self.cluster_beat_s,
                    missed_beats=self.cluster_missed_beats,
                )
                self.cluster_node_id = self.node_id
                if self.reqtrace is not None:
                    self.reqtrace.node_id = self.node_id
                self._reaper = threading.Thread(
                    target=self._reap_loop,
                    name="tpusim-cluster-reap", daemon=True,
                )
                self._reaper.start()
            return self.cluster

    def _reap_loop(self) -> None:
        while not self._stop_cluster.wait(self.cluster_beat_s):
            reg = self.cluster
            if reg is None:
                return
            died = reg.reap()
            if died and self.verbose:
                print(
                    f"tpusim serve: cluster marked dead: "
                    f"{', '.join(died)} (epoch {reg.epoch})"
                )

    def _watchdog_shedding(self) -> bool:
        return self.watchdog is not None and self.watchdog.shedding

    def cluster_shed(self) -> bool:
        """Node-grain load shed: with the alive-node count below the
        configured floor, queueing more work onto the survivors only
        converts overload into timeouts — answer 503 + Retry-After and
        let the balancer back off until the fleet heals."""
        if self.cluster_min_nodes <= 1:
            return False
        view = self.cluster_view_doc()
        if view is None:
            return False
        return len(alive_members(view)) < self.cluster_min_nodes

    def cluster_owner_url(self, endpoint: str, body: dict) -> str | None:
        """Where a simulate/lint request's affinity key lives: the
        owning node's public URL, or None when this node should serve
        it (owner == self, ring too small, or no cluster).  The key is
        the supervisor's volatile-stripped affinity hash, so cache
        identity is node-invariant by construction."""
        view = self.cluster_view_doc()
        if view is None:
            return None
        ring = ring_for(view)
        if len(ring) < 2:
            return None
        owner = ring.owner(Supervisor.affinity_key(endpoint, body))
        if owner is None or owner == self.node_id:
            return None
        return member_url(view, owner)

    def _fetch_node_json(self, url: str, path: str) -> dict | None:
        """GET a peer NODE's ``path`` (public URL; the acceptor-grain
        twin is :meth:`_fetch_peer_json` over direct ports)."""
        import http.client
        from urllib.parse import urlsplit

        try:
            u = urlsplit(url)
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=0.8,
            )
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
            conn.close()
            if resp.status != 200:
                return None
            return json.loads(payload)
        except (OSError, ValueError, http.client.HTTPException):
            return None

    def node_stats_values(self) -> dict[str, float]:
        """This NODE's raw metric values — acceptor-fleet-merged in
        front mode, plain local otherwise.  Served on the
        cluster-internal ``/v1/cluster/stats`` route so peers merge
        node-grain numbers, never double-counting acceptors."""
        if self.in_fleet:
            merged, _alive = self._merged_acceptor_values()
            return merged
        return self.metrics_values()

    def cluster_metrics_text(self) -> str:
        """Node-grain ``/metrics``: every alive member's node-local
        values merged (counters sum, shared-resource gauges max), plus
        ``serve_nodes_alive`` / ``serve_nodes_configured``.  The
        registry's own counters ride in via the primary's values —
        exactly one writer per key across the whole cluster."""
        view = self.cluster_view_doc() or {}
        members = [
            m for m in view.get("members", ()) if isinstance(m, dict)
        ]
        merged = self.node_stats_values()
        alive = 1
        for m in members:
            if not m.get("alive") or m.get("node_id") == self.node_id:
                continue
            doc = self._fetch_node_json(
                str(m.get("url")), "/v1/cluster/stats",
            )
            vals = (doc or {}).get("values")
            if not isinstance(vals, dict):
                continue
            alive += 1
            self._merge_values(merged, vals)
        merged["serve_nodes_alive"] = alive
        merged["serve_nodes_configured"] = max(len(members), 1)
        return self._render_metrics(merged)

    def cluster_healthz(self) -> dict:
        """The node-grain ``/healthz``: the local (or acceptor-merged)
        doc plus a cluster section; ``degraded`` while any configured
        member is dead."""
        doc = (
            self.fleet_healthz() if self.in_fleet
            else self.local_healthz()
        )
        view = self.cluster_view_doc() or {}
        members = [
            m for m in view.get("members", ()) if isinstance(m, dict)
        ]
        alive = sum(1 for m in members if m.get("alive"))
        doc["cluster"] = {
            "epoch": view.get("epoch"),
            "node_id": self.node_id,
            "primary": self.cluster is not None,
            "nodes_alive": alive,
            "nodes_configured": len(members),
        }
        if alive < len(members):
            doc["status"] = "degraded"
        return doc

    # -- hot-response tier (serve v3) ----------------------------------------

    def fleet_traces_doc(self, local_docs: list, limit: int = 50) -> list:
        """Fleet-merged slow-trace summaries: this acceptor's plus every
        peer's local list, re-sorted slowest first.  Any acceptor can
        answer ``GET /v1/debug/traces`` for the whole fleet."""
        docs = list(local_docs)
        for _idx, doc in self._fetch_peers_json(
            "/v1/debug/traces?scope=local"
        ).items():
            peer_traces = (doc or {}).get("traces")
            if isinstance(peer_traces, list):
                docs.extend(
                    t for t in peer_traces if isinstance(t, dict)
                )
        docs.sort(key=lambda t: t.get("total_ms", 0.0), reverse=True)
        return docs[: max(int(limit), 0)]

    def fleet_trace_get(self, trace_id: str) -> dict | None:
        """By-ID fleet fallback: ask every peer's local recorder for a
        trace this acceptor never saw (requests balance across
        acceptors, so the slowest trace rarely lives where the debug
        query lands)."""
        from tpusim.obs.reqtrace import valid_trace_id

        if not valid_trace_id(trace_id):
            return None
        for _idx, doc in self._fetch_peers_json(
            f"/v1/debug/traces/{trace_id}?scope=local"
        ).items():
            trace = (doc or {}).get("trace")
            if isinstance(trace, dict):
                return trace
        return None

    def _trace_fingerprint(self, name: str) -> str | None:
        """A cheap stat fingerprint of one named trace directory
        (file names + sizes + mtimes), cached per name.  Joins the hot
        key so a hot dir surviving a daemon restart can never serve
        bytes priced from different on-disk trace content."""
        with self._trace_fp_lock:
            fp = self._trace_fp_cache.get(name)
        if fp is not None:
            return fp
        root = self.registry.trace_root
        if root is None:
            return None
        path = root / name
        if not path.is_dir():
            return None
        import hashlib

        parts = []
        try:
            for p in sorted(path.rglob("*")):
                if p.is_file():
                    st = p.stat()
                    parts.append(
                        f"{p.relative_to(path)}:{st.st_size}:"
                        f"{st.st_mtime_ns}"
                    )
        except OSError:
            return None
        fp = hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]
        with self._trace_fp_lock:
            self._trace_fp_cache[name] = fp
        return fp

    def hot_key_for(self, endpoint: str, body: dict) -> str | None:
        """The hot-cache identity of one request, or None when the
        request is not hot-servable (no hot tier, or a named trace we
        cannot fingerprint).  Built on the supervisor's affinity hash —
        the same volatile-key stripping, so deadlines never fragment
        the hot tier."""
        if self.hot is None or not isinstance(body, dict):
            return None
        from tpusim.serve.supervisor import Supervisor

        key = Supervisor.affinity_key(endpoint, body)
        trace = body.get("trace")
        if trace is not None:
            fp = self._trace_fingerprint(str(trace))
            if fp is None:
                return None  # unknown trace: let the 404 path answer
            key = f"{key}-{fp}"
        return key

    def hot_publish(self, hot_key: str, result) -> None:
        """Publish one successful simulate response in WARM form: the
        exact bytes a repeat (result-cache-hit) request would produce —
        ``cache_hit`` true, the per-request cache accounting folded to
        its steady state (every get that missed cold hits on replay).
        First writer wins across acceptors; all produced byte-identical
        pricing by the serving contract."""
        import json as _json

        try:
            if isinstance(result, (bytes, bytearray, memoryview)):
                doc = _json.loads(bytes(result))
            else:
                doc = {
                    "format_version": SERVE_FORMAT_VERSION,
                    "model_version": self.worker.model_version,
                    **result,
                }
            if not doc.get("cache_hit", False):
                doc = dict(doc)
                doc["cache_hit"] = True
                stats = doc.get("stats")
                if isinstance(stats, dict) and "cache_misses" in stats:
                    # fold the per-request accounting to its warm form,
                    # PRESERVING numeric types — an int 0 and a float
                    # 0.0 serialize differently, and these bytes must
                    # equal a real warm response's exactly
                    stats = dict(stats)
                    misses = stats["cache_misses"]
                    stats["cache_hits"] = (
                        stats.get("cache_hits", 0) + misses
                    )
                    stats["cache_misses"] = type(misses)(0)
                    doc["stats"] = stats
            body = _json.dumps(doc, sort_keys=True).encode() + b"\n"
            # publishes ride /metrics from the hot store's own counter
            self.hot.publish(hot_key, body)
        except (OSError, ValueError, TypeError):
            self._count("serve_hot_publish_errors_total")

    # -- sync dispatch -------------------------------------------------------

    def execute_sync(self, endpoint: str, fn, body: dict, deadline: float,
                     reqtrace=None):
        """One admitted synchronous request: through the supervised
        worker pool when mounted (crash isolation, cooperative deadline
        cancel with kill escalation, quarantine — the serve v2 path),
        else the in-process worker (``fn``) pricing under a
        :class:`~tpusim.guard.CancelToken` armed with the same deadline.
        Responses are byte-identical either way.  ``reqtrace`` collects
        the worker-side tier spans (both paths time over the shared
        monotonic clock, so they merge without alignment)."""
        if self.supervisor is not None:
            return self.supervisor.execute(
                endpoint, body, deadline=deadline, reqtrace=reqtrace,
            )
        cancel = CancelToken(deadline=deadline)
        if reqtrace is None:
            return fn(body, cancel=cancel)
        spans: list = []
        try:
            result = fn(body, cancel=cancel, spans=spans)
        finally:
            reqtrace.add_worker_spans(spans)
        if isinstance(result, dict) and "cache_hit" in result:
            reqtrace.meta["tier"] = (
                "warm" if result.get("cache_hit") else "priced"
            )
        return result

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        if self._httpd is None:
            return self._requested_port
        return self._httpd.server_address[1]

    @property
    def direct_port(self) -> int | None:
        """The fleet-internal listener's port (serve v3; None when this
        daemon is not an acceptor)."""
        if self._direct_httpd is None:
            return None
        return self._direct_httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def inject_connection(self, sock, addr, accepted_s=None) -> None:
        """Dispatch one already-accepted connection into this daemon's
        HTTP stack — the fd-passing fallback path on kernels without
        ``SO_REUSEPORT`` (the front parent accepts and ships the fd via
        ``socket.send_fds``; this acceptor parses and serves it).
        ``accepted_s`` is the front parent's monotonic accept timestamp
        (shared clock): when tracing is on, the first request on this
        connection gets an ``fd_dispatch`` span covering the handoff."""
        if self.reqtrace is not None and accepted_s is not None:
            self._note_accepted(sock, float(accepted_s))
        server = self._direct_httpd or self._httpd
        server.process_request(sock, addr)

    def _note_accepted(self, sock, accepted_s: float) -> None:
        with self._accept_lock:
            if len(self._accept_ts) > 1024:
                # connections that never issued a request would leak
                # their stamps; a full map means exactly that — reset
                self._accept_ts.clear()
            self._accept_ts[id(sock)] = (accepted_s, time.monotonic())

    def pop_accept_ts(self, sock) -> tuple[float, float] | None:
        """Claim (once) the fd-passing accept/handoff timestamps for a
        handler's connection; None on the reuseport/direct path."""
        if not self._accept_ts:
            return None
        with self._accept_lock:
            return self._accept_ts.pop(id(sock), None)

    def start(self) -> "ServeDaemon":
        """Bind the listener and start serving on background threads.
        Returns self (so tests can ``ServeDaemon(...).start()``)."""
        _prewarm_pricing_stack()
        sweep_dirs = []
        if self.result_cache.disk_dir is not None \
                and self.result_cache.disk_dir.is_dir():
            sweep_dirs.append(self.result_cache.disk_dir)
        if self.compile_store is not None \
                and self.compile_store.disk_dir.is_dir() \
                and self.compile_store.disk_dir not in sweep_dirs:
            # a compiled tier mounted at its own dir gets the same boot
            # sweep (verify_store is tier-aware; a shared dir is swept
            # once and covers both record kinds)
            sweep_dirs.append(self.compile_store.disk_dir)
        for sweep_dir in sweep_dirs:
            # startup integrity sweep (tpusim.guard): quarantine corrupt
            # or stale-format records BEFORE the first request can trip
            # over them — a crashed peer's damage heals at boot, not one
            # warning at a time under traffic
            from tpusim.guard.store import verify_store

            res = verify_store(sweep_dir)
            # accumulate: a daemon may sweep the result dir AND a
            # separately-mounted compiled dir
            for key, add in (
                ("startup_records_checked", res.checked),
                ("startup_records_ok", res.ok),
                ("startup_quarantined",
                 res.quarantined_corrupt + res.quarantined_stale_format),
                ("startup_stale_model", res.stale_model),
            ):
                self._guard_startup[key] = (
                    self._guard_startup.get(key, 0) + add
                )
            if self.verbose and (
                res.quarantined_corrupt or res.quarantined_stale_format
            ):
                print(
                    f"tpusim serve: startup sweep quarantined "
                    f"{res.quarantined_corrupt} corrupt + "
                    f"{res.quarantined_stale_format} stale-format "
                    f"cache record(s)"
                )
        if self.watchdog is not None:
            self.watchdog.start()
        import os as _os

        for fd in self._close_fds:
            # fds inherited from a front supervisor (its port-reserve
            # socket, siblings' pipe ends): close them so a dead parent
            # releases its resources regardless of acceptor lifetimes
            try:
                _os.close(int(fd))
            except (OSError, ValueError, TypeError):
                pass
        handler = type(
            "BoundHandler", (_Handler,), {"daemon_obj": self},
        )

        class _Server(ThreadingHTTPServer):
            # most clients (urllib included) open a fresh connection
            # per request; the stdlib backlog of 5 overflows under any
            # real concurrency and SYN retransmits (~1s) then dwarf the
            # service time
            request_queue_size = 128

        class _ReusePortServer(_Server):
            # serve v3: N acceptor processes each bind their own
            # listening socket on the SAME port; the kernel distributes
            # incoming connections across the reuseport group — no
            # single process ever parses every request
            def server_bind(self):
                import socket as _socket

                self.socket.setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1,
                )
                super().server_bind()

        self._httpd = None
        if self.public_listener:
            server_cls = _ReusePortServer if self.reuse_port else _Server
            self._httpd = server_cls(
                (self.host, self._requested_port), handler,
            )
            self._httpd.daemon_threads = True
        self._direct_httpd = None
        if self.in_fleet:
            # the fleet-internal listener: peer /-/stats merges, job
            # proxying to the primary, and (fd-passing fallback mode)
            # the server object injected connections dispatch through
            self._direct_httpd = _Server((self.host, 0), handler)
            self._direct_httpd.daemon_threads = True
        if self.supervisor is not None:
            # forked workers inherit the freshly-bound listeners; they
            # close them first thing (the fds travel via settings) so a
            # dead daemon's port is never held open by its workers
            self.supervisor.settings["inherited_fds"] = [
                s.fileno() for s in (self._httpd, self._direct_httpd)
                if s is not None
            ] + [int(f) for f in self._worker_close_fds]
            self.supervisor.start()
        if self._httpd is not None:
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="tpusim-serve-accept", daemon=True,
            )
            self._serve_thread.start()
        if self._direct_httpd is not None:
            self._direct_thread = threading.Thread(
                target=self._direct_httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="tpusim-serve-direct", daemon=True,
            )
            self._direct_thread.start()
        if self.cluster_join is not None:
            from tpusim.serve.cluster import HeartbeatLoop

            # a --join member is clustered from boot; started here
            # because node_id needs the BOUND public port
            self.cluster_node_id = self.node_id
            if self.reqtrace is not None:
                self.reqtrace.node_id = self.node_id
            self._heartbeat = HeartbeatLoop(
                node_id=self.node_id, url=self.url,
                join_addr=self.cluster_join,
                interval_s=self.cluster_beat_s,
                on_view=self._on_cluster_view,
                shedding=self._watchdog_shedding,
            ).start()
        for i in range(self._job_workers):
            t = threading.Thread(
                target=self._job_loop, name=f"tpusim-serve-job-{i}",
                daemon=True,
            )
            t.start()
            self._job_threads.append(t)
        return self

    def campaign_dir(self, job_id: str):
        """Where one campaign job journals (None without --state-dir:
        the job still runs, it just cannot survive a crash)."""
        if self.state_dir is None:
            return None
        return self.state_dir / "campaigns" / job_id

    def fleet_dir(self, job_id: str):
        """Where one fleet job journals — the campaign discipline
        under its own subtree."""
        if self.state_dir is None:
            return None
        return self.state_dir / "fleet" / job_id

    def _evict_job_state(self, job_id: str) -> None:
        import shutil

        for d in (self.campaign_dir(job_id), self.fleet_dir(job_id)):
            if d is not None and d.is_dir():
                shutil.rmtree(d, ignore_errors=True)

    def _run_job(self, job) -> dict:
        if job.kind == "campaign":
            return self.worker.campaign(
                job.request, out_dir=self.campaign_dir(job.job_id),
                cancel=job.cancel_token,
            )
        if job.kind == "fleet":
            return self.worker.fleet(
                job.request, out_dir=self.fleet_dir(job.job_id),
                cancel=job.cancel_token,
            )
        if job.kind == "advise":
            # no journal: an advise sweep is cache-warm cheap, so a
            # recovered job simply re-prices (byte-identical by the
            # determinism contract)
            return self.worker.advise(job.request, cancel=job.cancel_token)
        return self.worker.sweep(job.request, cancel=job.cancel_token)

    def _job_loop(self) -> None:
        while True:
            job = self.jobs.next_job(timeout_s=0.2)
            if job is None:
                if self._stop_jobs.is_set():
                    return
                continue
            try:
                result = self._run_job(job)
            except OperationCancelled as e:
                # DELETE /v1/jobs/<id> landed mid-run: the runner
                # unwound at a scenario/cell boundary with completed
                # work journaled — terminal 'cancelled', not 'failed'
                self.jobs.finish(
                    job, None, f"cancelled: {e}", status="cancelled",
                )
                self._count("serve_jobs_cancelled_total")
            except RequestError as e:
                self.jobs.finish(job, None, f"{e.code}: {e.detail}")
                self._count("serve_jobs_failed_total")
            except Exception as e:  # noqa: BLE001 - job boundary
                self.jobs.finish(job, None, f"{type(e).__name__}: {e}")
                self._count("serve_jobs_failed_total")
            else:
                self.jobs.finish(job, result, None)
                self._count("serve_jobs_done_total")

    def drain_and_stop(self) -> bool:
        """The SIGTERM sequence: stop admitting, finish in-flight work
        and accepted jobs, flush the disk cache, close the listener.
        Returns True when everything drained inside the grace period."""
        self._stop_cluster.set()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        self.admission.start_drain()
        self.jobs.start_drain()
        clean = self.admission.wait_idle(self.drain_grace_s)
        clean = self.jobs.wait_idle(self.drain_grace_s) and clean
        self._stop_jobs.set()
        for t in self._job_threads:
            t.join(timeout=2.0)
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.supervisor is not None:
            self.supervisor.stop()
        flushed = self.result_cache.flush()
        if self.verbose and flushed:
            print(f"tpusim serve: drain flushed {flushed} cache records")
        for srv in (self._httpd, self._direct_httpd):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        if self.access_log is not None:
            self.access_log.close()
        self._stopped.set()
        return clean

    def abort(self) -> None:
        """Stop WITHOUT draining — the crash-simulation path (tests,
        emergency teardown): listener closed, job threads told to stop,
        queued/running jobs left exactly as persisted so a fresh daemon
        on the same ``state_dir`` recovers them."""
        self._stop_cluster.set()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        self._stop_jobs.set()
        for t in self._job_threads:
            t.join(timeout=2.0)
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.supervisor is not None:
            # crash simulation still reaps the fleet: orphan workers
            # would hold the (inherited) state the next daemon needs
            self.supervisor.stop(grace_s=0.2)
        for srv in (self._httpd, self._direct_httpd):
            if srv is not None:
                srv.shutdown()
                srv.server_close()
        if self.access_log is not None:
            self.access_log.close()
        self._stopped.set()

    def wait_stopped(self, timeout_s: float | None = None) -> bool:
        return self._stopped.wait(timeout_s)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → drain on a helper thread (the handler runs
        on the main thread, which may be blocked in ``wait_stopped``;
        ``shutdown()`` must never be called from the accept loop)."""

        def _on_signal(signum, frame):
            threading.Thread(
                target=self.drain_and_stop,
                name="tpusim-serve-drain", daemon=True,
            ).start()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    # -- context manager (tests) ---------------------------------------------

    def __enter__(self) -> "ServeDaemon":
        return self.start()

    def __exit__(self, *exc) -> bool:
        if not self._stopped.is_set():
            self.drain_and_stop()
        return False
