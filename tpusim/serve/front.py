"""Multi-acceptor front tier — serve v3's scaling core.

``reports/serve_bench.json`` (PR 9) proved the fleet **parent-bound**:
one stdlib-threaded HTTP parent caps warm throughput at ~450 req/s, and
adding workers *reduces* it — HTTP parse + dispatch under a single GIL
is the ceiling, not pricing.  This module removes the single parent: N
**acceptor processes** each run a full :class:`~tpusim.serve.daemon.
ServeDaemon` (their own HTTP parse, admission, registry, optional
supervised worker pool) and share ONE public port via ``SO_REUSEPORT``
— the kernel distributes connections across the fleet, so no single
GIL ever touches every request.

Topology:

* **acceptors** — forked up front (spawn fallback), supervised by the
  parent :class:`FrontSupervisor`: crash detection, exponential-backoff
  restarts with deterministic jitter, peer-map rebroadcast on
  membership change.  The parent serves no HTTP itself; it holds a
  bound-but-not-listening reuseport socket purely to reserve the port
  (only LISTENING sockets join the kernel's delivery group).
* **acceptor 0 is the primary** — sole owner of the async
  :class:`~tpusim.serve.admission.JobTable` (ids, persistence, restart
  recovery stay single-writer); the others proxy job-family routes to
  its direct listener over loopback.
* **shared state** — the disk result-cache tier (L2, quota-governed by
  every writer), the mmap :class:`~tpusim.serve.hotcache.
  HotResponseCache` (any acceptor publishes, all serve from it), and
  the poison-quarantine directory (a request that killed workers behind
  one acceptor is refused by all).
* **fallback** (kernels without ``SO_REUSEPORT``, or
  ``TPUSIM_NO_REUSEPORT=1``) — the parent binds the one listener,
  accepts, and ships each connection's fd round-robin to an acceptor
  over a unix socketpair via :func:`socket.send_fds`; the acceptor
  rebuilds the socket and dispatches it into its own HTTP stack.  Same
  fleet semantics, one extra syscall per connection.

Byte-identity holds across every topology by construction: each
acceptor runs the exact serving stack the standalone daemon does, and
the hot tier stores final response bytes those stacks produced.
"""

from __future__ import annotations

import multiprocessing
import os
import signal
import socket
import struct
import threading
import time

__all__ = ["AcceptorSlot", "FrontSupervisor", "acceptor_main",
           "reuse_port_available"]

#: restart backoff ceiling for crashed acceptors
MAX_RESTART_BACKOFF_S = 30.0

#: how long one acceptor boot may take before the spawn is abandoned
ACCEPTOR_READY_TIMEOUT_S = 60.0


def reuse_port_available() -> bool:
    """True when this kernel (and this run) can use ``SO_REUSEPORT``.
    ``TPUSIM_NO_REUSEPORT=1`` forces the fd-passing fallback — the
    contract tests exercise both paths on any host."""
    if os.environ.get("TPUSIM_NO_REUSEPORT", "") not in ("", "0"):
        return False
    return hasattr(socket, "SO_REUSEPORT")


def _det_jitter(index: int, spawns: int, base: float) -> float:
    import hashlib

    h = hashlib.sha256(f"front:{index}:{spawns}".encode()).digest()
    return 0.25 * base * (int.from_bytes(h[:4], "big") / 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Acceptor child
# ---------------------------------------------------------------------------


def acceptor_main(index: int, conn, settings: dict) -> None:
    """Entry point of one acceptor process.

    ``settings`` is the picklable bootstrap document: every
    :class:`~tpusim.serve.daemon.ServeDaemon` constructor knob plus
    ``host``/``public_port``/``reuse_port``/``fd_mode``/``close_fds``.
    The protocol over ``conn``: the child sends ``("ready", pid,
    direct_port)`` once serving; the parent pushes ``("peers", {index:
    direct_port}, primary_direct)`` on every membership change and
    ``None`` as the drain-and-exit sentinel.  In fd mode the acceptor
    additionally drains accepted-connection fds from ``settings
    ['fd_sock_fileno']`` (its end of the inherited socketpair).
    """
    import sys

    from tpusim.serve.daemon import ServeDaemon

    signal.signal(signal.SIGINT, signal.SIG_IGN)
    fd_mode = bool(settings.get("fd_mode"))
    daemon = ServeDaemon(
        trace_root=settings.get("trace_root"),
        host=settings.get("host", "127.0.0.1"),
        port=int(settings.get("public_port", 0)),
        max_inflight=settings.get("max_inflight", 4),
        queue_depth=settings.get("queue_depth", 16),
        deadline_s=settings.get("deadline_s", 30.0),
        max_request_bytes=settings.get(
            "max_request_bytes", 8 * 1024 * 1024
        ),
        result_cache=settings.get("result_cache"),
        cache_entries=settings.get("cache_entries", 4096),
        workers=settings.get("workers", 1),
        serve_workers=settings.get("workers_per_acceptor", 0),
        min_workers=settings.get("min_workers", 1),
        restart_backoff_s=settings.get("restart_backoff_s", 0.05),
        chaos_hooks=settings.get("chaos_hooks", False),
        # only the primary drains jobs; secondaries proxy to it
        job_workers=(
            settings.get("job_workers", 1) if index == 0 else 0
        ),
        job_queue_depth=settings.get("job_queue_depth", 16),
        drain_grace_s=settings.get("drain_grace_s", 60.0),
        state_dir=settings.get("state_dir") if index == 0 else None,
        verbose=settings.get("verbose", False),
        cache_quota=settings.get("disk_quota"),
        max_rss=settings.get("max_rss"),
        max_worker_rss=settings.get("max_worker_rss"),
        compile_cache=settings.get("compile_cache"),
        hot_cache=settings.get("hot_cache"),
        hot_quota_bytes=settings.get("hot_quota_bytes"),
        strict_lint=settings.get("strict_lint", False),
        trace_requests=settings.get("trace_requests", False),
        access_log=settings.get("access_log"),
        # cluster membership is a PRIMARY concern: acceptor 0 owns the
        # registry/heartbeat (the JobTable discipline); secondaries
        # proxy /v1/cluster/* to it over the direct listener
        cluster_join=(
            settings.get("join_addr") if index == 0 else None
        ),
        cluster_min_nodes=(
            settings.get("join_min_nodes", 1) if index == 0 else 1
        ),
        acceptor_index=index,
        acceptors_total=settings.get("acceptors_total", 0),
        reuse_port=not fd_mode and bool(settings.get("reuse_port", True)),
        public_listener=not fd_mode,
        quarantine_dir=settings.get("quarantine_dir"),
        close_fds=settings.get("close_fds") or (),
        # this acceptor's own channels: ITS workers must not inherit
        # them alive (a worker pinning the fd-passing socketpair would
        # let the parent ship connections into a dead acceptor)
        worker_close_fds=[
            fd for fd in (
                conn.fileno(),
                settings.get("fd_sock_fileno"),
            ) if fd is not None
        ],
    )
    # SIGTERM drains THIS acceptor (the front parent coordinates the
    # fleet; a directly-TERMed acceptor still exits clean on its own)
    drained = threading.Event()

    def _drain_and_exit(*_a):
        if drained.is_set():
            return
        drained.set()

        def _run():
            daemon.drain_and_stop()
            os._exit(0)  # the control loop may be blocked in recv()

        threading.Thread(target=_run, daemon=True).start()

    signal.signal(signal.SIGTERM, _drain_and_exit)
    try:
        daemon.start()
    except OSError as e:
        try:
            conn.send(("bind_error", os.getpid(), str(e)))
        except (BrokenPipeError, OSError):
            pass
        return
    if fd_mode:
        fd_sock = socket.socket(
            socket.AF_UNIX, socket.SOCK_STREAM,
            fileno=int(settings["fd_sock_fileno"]),
        )

        def _fd_loop():
            while True:
                try:
                    msg, fds, _flags, _addr = socket.recv_fds(
                        fd_sock, 16, 4,
                    )
                except OSError:
                    return
                if not fds:
                    return  # parent closed its end: we are draining
                # the parent stamps its monotonic accept time into the
                # send_fds message (shared clock across fork): request
                # tracing turns it into the fd_dispatch span
                accepted_s = None
                if len(msg) >= 8:
                    try:
                        accepted_s = struct.unpack("<d", msg[:8])[0]
                    except struct.error:
                        accepted_s = None
                for fd in fds:
                    try:
                        client = socket.socket(fileno=fd)
                    except OSError:
                        try:
                            os.close(fd)
                        except OSError:
                            pass
                        continue
                    # from here the socket object OWNS the fd: close
                    # through it, never os.close (a raw close plus the
                    # object's own close would release the number twice
                    # — the second close could hit an unrelated fd a
                    # concurrent thread was just assigned)
                    try:
                        daemon.inject_connection(
                            client, client.getpeername(),
                            accepted_s=accepted_s,
                        )
                    except OSError:
                        try:
                            client.close()
                        except OSError:
                            pass

        threading.Thread(
            target=_fd_loop, name="tpusim-front-fdrecv", daemon=True,
        ).start()
    try:
        conn.send(("ready", os.getpid(), daemon.direct_port))
    except (BrokenPipeError, OSError):
        daemon.abort()
        return
    # control loop: peer pushes + the drain sentinel.  EOF (the parent
    # died) drains too — an orphan acceptor must not serve forever.
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            msg = None
        if msg is None:
            if not drained.is_set():
                drained.set()
                daemon.drain_and_stop()
            sys.exit(0)
        if isinstance(msg, tuple) and msg and msg[0] == "peers":
            daemon.set_peers(msg[1], msg[2])


# ---------------------------------------------------------------------------
# Front supervisor (parent)
# ---------------------------------------------------------------------------


class AcceptorSlot:
    """One supervised acceptor position."""

    def __init__(self, index: int):
        self.index = index
        self.proc = None
        self.conn = None
        self.fd_sock = None          # parent end of the fd socketpair
        self.pid: int | None = None
        self.direct_port: int | None = None
        self.alive = False
        self.spawns = 0
        self.boots = 0
        self.consecutive_failures = 0
        self.next_restart_at = 0.0

    @property
    def restarts(self) -> int:
        return max(self.boots - 1, 0)


class FrontSupervisor:
    """Owns the acceptor fleet: port reservation, spawn/restart,
    peer-map broadcast, and (fallback mode) the accept+fd-ship loop."""

    def __init__(
        self,
        settings: dict,
        num_acceptors: int = 2,
        host: str = "127.0.0.1",
        port: int = 0,
        restart_backoff_s: float = 0.2,
    ):
        self.settings = dict(settings)
        self.num_acceptors = max(int(num_acceptors), 1)
        self.host = host
        self._requested_port = int(port)
        self.port: int | None = None
        self.restart_backoff_s = max(float(restart_backoff_s), 0.01)
        self.reuse_port = reuse_port_available()
        self.slots = [AcceptorSlot(i) for i in range(self.num_acceptors)]
        self._reserve_sock: socket.socket | None = None
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._monitor: threading.Thread | None = None
        self._accept_thread: threading.Thread | None = None
        self._fd_rr = 0
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FrontSupervisor":
        from tpusim.perf.pool import DeferSignals

        if self.reuse_port:
            # reserve the port WITHOUT joining the delivery group: a
            # bound-but-not-listening reuseport socket holds the number
            # while only the acceptors' listening sockets receive
            self._reserve_sock = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM,
            )
            self._reserve_sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1,
            )
            self._reserve_sock.bind((self.host, self._requested_port))
            self.port = self._reserve_sock.getsockname()[1]
        else:
            # fd-passing fallback: the parent owns the one listener
            self._listener = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM,
            )
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1,
            )
            self._listener.bind((self.host, self._requested_port))
            self._listener.listen(128)
            self.port = self._listener.getsockname()[1]
        with DeferSignals():
            for slot in self.slots:
                ok = self._spawn(slot)
                if not ok and slot.index == 0:
                    # without a primary nothing async works; refuse to
                    # start a half-fleet silently
                    self.stop(grace_s=1.0)
                    raise RuntimeError(
                        "front tier failed to boot acceptor 0"
                    )
        self._broadcast_peers()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tpusim-front-supervisor",
            daemon=True,
        )
        self._monitor.start()
        if not self.reuse_port:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name="tpusim-front-accept",
                daemon=True,
            )
            self._accept_thread.start()
        return self

    def _child_settings(self, slot: AcceptorSlot) -> dict:
        s = dict(self.settings)
        s["host"] = self.host
        s["public_port"] = self.port
        s["reuse_port"] = self.reuse_port
        s["fd_mode"] = not self.reuse_port
        s["acceptors_total"] = self.num_acceptors
        close_fds = []
        if self._reserve_sock is not None:
            close_fds.append(self._reserve_sock.fileno())
        if self._listener is not None:
            close_fds.append(self._listener.fileno())
        # siblings' fd-socketpair parent ends AND control-pipe parent
        # ends travel into every fork; each child closes the ones that
        # are not its own.  The pipe ends matter for orphan drain: an
        # acceptor holding a sibling's pipe write end would keep that
        # sibling's conn.recv() from ever seeing EOF after the parent
        # dies — both orphans would serve the reuseport group forever.
        for other in self.slots:
            if other is slot:
                continue
            if other.fd_sock is not None:
                close_fds.append(other.fd_sock.fileno())
            if other.conn is not None:
                try:
                    close_fds.append(other.conn.fileno())
                except OSError:
                    pass
        s["close_fds"] = close_fds
        return s

    def _spawn(self, slot: AcceptorSlot) -> bool:
        methods = multiprocessing.get_all_start_methods()
        method = "fork" if "fork" in methods else "spawn"
        ctx = multiprocessing.get_context(method)
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        child_fd_sock = None
        if not self.reuse_port:
            parent_fd, child_fd_sock = socket.socketpair(
                socket.AF_UNIX, socket.SOCK_STREAM,
            )
            slot.fd_sock = parent_fd
        settings = self._child_settings(slot)
        if child_fd_sock is not None:
            settings["fd_sock_fileno"] = child_fd_sock.fileno()
        if method != "fork":
            settings["close_fds"] = []
        proc = ctx.Process(
            target=acceptor_main,
            args=(slot.index, child_conn, settings),
            name=f"tpusim-front-acceptor-{slot.index}",
            daemon=False,  # acceptors own worker children of their own
        )
        slot.spawns += 1
        try:
            proc.start()
        except OSError:
            parent_conn.close()
            self._mark_failed(slot)
            return False
        finally:
            child_conn.close()
            if child_fd_sock is not None:
                child_fd_sock.close()
        ready = False
        direct_port = None
        pid = None
        try:
            if parent_conn.poll(ACCEPTOR_READY_TIMEOUT_S):
                msg = parent_conn.recv()
                if (
                    isinstance(msg, tuple) and len(msg) == 3
                    and msg[0] == "ready"
                ):
                    ready, pid, direct_port = True, msg[1], msg[2]
        except (EOFError, OSError):
            ready = False
        if not ready:
            try:
                proc.kill()
                proc.join(1.0)
            except (OSError, ValueError):
                pass
            parent_conn.close()
            self._mark_failed(slot)
            return False
        with self._lock:
            if self._stop.is_set():
                registered = False
            else:
                slot.proc = proc
                slot.conn = parent_conn
                slot.pid = pid
                slot.direct_port = direct_port
                slot.alive = True
                slot.boots += 1
                slot.consecutive_failures = 0
                registered = True
        if not registered:
            # stop() won the lock first: its sentinel sweep is over, so
            # this fresh acceptor would never hear the drain — tear it
            # down here instead of leaking a live process that keeps
            # serving the reuseport group (the supervisor.py idiom)
            try:
                parent_conn.send(None)
            except (BrokenPipeError, OSError):
                pass
            proc.join(5.0)
            if proc.is_alive():
                try:
                    proc.kill()
                    proc.join(1.0)
                except (OSError, ValueError):
                    pass
            parent_conn.close()
            return False
        return True

    def _mark_failed(self, slot: AcceptorSlot) -> None:
        with self._lock:
            slot.alive = False
            slot.pid = None
            slot.consecutive_failures += 1
            base = self.restart_backoff_s * (
                2.0 ** max(slot.consecutive_failures - 1, 0)
            )
            base = min(base, MAX_RESTART_BACKOFF_S)
            slot.next_restart_at = time.monotonic() + base + _det_jitter(
                slot.index, slot.spawns, base,
            )
        if slot.fd_sock is not None:
            try:
                slot.fd_sock.close()
            except OSError:
                pass
            slot.fd_sock = None

    def _broadcast_peers(self) -> None:
        with self._lock:
            peers = {
                s.index: s.direct_port
                for s in self.slots
                if s.alive and s.direct_port is not None
            }
            primary = peers.get(0)
            conns = [
                (s, s.conn) for s in self.slots if s.alive and s.conn
            ]
        for slot, conn in conns:
            try:
                conn.send(("peers", peers, primary))
            except (BrokenPipeError, OSError):
                pass  # the monitor will notice the death

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.05):
            changed = False
            for slot in self.slots:
                if self._stop.is_set():
                    return
                proc = slot.proc
                if slot.alive and proc is not None and not proc.is_alive():
                    self._on_death(slot)
                    changed = True
                elif (
                    not slot.alive
                    and time.monotonic() >= slot.next_restart_at
                ):
                    if self._spawn(slot):
                        changed = True
            if changed and not self._stop.is_set():
                self._broadcast_peers()

    def _on_death(self, slot: AcceptorSlot) -> None:
        with self._lock:
            slot.alive = False
            slot.pid = None
            slot.consecutive_failures += 1
            base = self.restart_backoff_s * (
                2.0 ** max(slot.consecutive_failures - 1, 0)
            )
            base = min(base, MAX_RESTART_BACKOFF_S)
            slot.next_restart_at = time.monotonic() + base + _det_jitter(
                slot.index, slot.spawns, base,
            )
        for res in (slot.conn, slot.fd_sock):
            if res is not None:
                try:
                    res.close()
                except OSError:
                    pass
        slot.conn = None
        slot.fd_sock = None
        if slot.proc is not None:
            try:
                slot.proc.join(0.1)
            except (OSError, ValueError):
                pass
        slot.proc = None

    def _accept_loop(self) -> None:
        """Fallback mode only: accept on the one listener and ship each
        connection's fd to a live acceptor round-robin."""
        while not self._stop.is_set():
            try:
                self._listener.settimeout(0.2)
                client, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sent = False
            for _ in range(len(self.slots)):
                with self._lock:
                    self._fd_rr = (self._fd_rr + 1) % len(self.slots)
                    slot = self.slots[self._fd_rr]
                    fd_sock = slot.fd_sock if slot.alive else None
                if fd_sock is None:
                    continue
                try:
                    # the message carries the accept timestamp (shared
                    # monotonic clock) for the acceptor's fd_dispatch
                    # span; receivers that predate it ignored the bytes
                    socket.send_fds(
                        fd_sock,
                        [struct.pack("<d", time.monotonic())],
                        [client.fileno()],
                    )
                    sent = True
                    break
                except OSError:
                    continue
            client.close()  # the acceptor holds its own duplicate now
            if not sent:
                # no live acceptor: the close above RSTs the client —
                # the same outcome as a daemon that is simply down
                pass

    # -- shutdown ------------------------------------------------------------

    def stop(self, grace_s: float = 60.0) -> bool:
        """Drain the fleet: sentinel to every acceptor, bounded join,
        SIGKILL stragglers.  Returns True when every acceptor exited
        inside the grace period."""
        with self._lock:
            # same lock _spawn registers under: a respawn in flight
            # either registered already (the sweep below reaps it) or
            # sees _stop at registration and tears its acceptor down
            self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for slot in self.slots:
            if slot.conn is not None:
                try:
                    slot.conn.send(None)
                except (BrokenPipeError, OSError):
                    pass
        clean = True
        deadline = time.monotonic() + max(grace_s, 0.5)
        for slot in self.slots:
            proc = slot.proc
            if proc is None:
                continue
            proc.join(max(deadline - time.monotonic(), 0.1))
            if proc.is_alive():
                clean = False
                try:
                    proc.terminate()
                    proc.join(2.0)
                    if proc.is_alive():
                        proc.kill()
                        proc.join(1.0)
                except (OSError, ValueError):
                    pass
            for res in (slot.conn, slot.fd_sock):
                if res is not None:
                    try:
                        res.close()
                    except OSError:
                        pass
            slot.conn = None
            slot.fd_sock = None
            slot.alive = False
        if self._reserve_sock is not None:
            try:
                self._reserve_sock.close()
            except OSError:
                pass
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        self._stopped.set()
        return clean

    def wait_stopped(self, timeout_s: float | None = None) -> bool:
        return self._stopped.wait(timeout_s)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → drain the fleet on a helper thread."""

        def _on_signal(signum, frame):
            threading.Thread(
                target=self.stop, name="tpusim-front-drain", daemon=True,
            ).start()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    # -- chaos / reporting ---------------------------------------------------

    def acceptor_pids(self) -> list[int | None]:
        return [s.pid for s in self.slots]

    def alive_count(self) -> int:
        return sum(1 for s in self.slots if s.alive)

    def kill_acceptor(self, index: int) -> None:
        """SIGKILL one acceptor outright (chaos testing)."""
        pid = self.slots[index].pid
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass

    # -- context manager (tests) ---------------------------------------------

    def __enter__(self) -> "FrontSupervisor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        if not self._stopped.is_set():
            self.stop()
        return False
