"""Shared mmap hot-response cache — serve v3's lock-free warm path.

The serve v2 bench proved the fleet parent-bound: for 100%-cache-hit
traffic the per-request cost is HTTP parse + admission + dispatch + a
result-cache lookup + JSON re-serialization, all under one GIL.  This
module removes everything after the parse: the **final serialized
response body** (the exact ``ok_bytes`` envelope a worker would produce)
is published once into an append-only shared segment, and every later
identical request is answered straight from an ``mmap`` of that segment
— no pickling, no dispatch, no re-pricing, no admission slot.  N
acceptor processes share one cache directory; any of them can publish,
all of them read.

Design (one writer discipline, lock-free readers):

* **segment** (``seg-<generation>[-<epoch>].dat``) — append-only raw
  response bytes.  Writers append under an ``flock`` on a sidecar lock
  file; the body is flushed and fsync'd BEFORE the index names it, so an
  index entry always points at fully-durable bytes.
* **index** (``index-<generation>.json``) — ``key -> [offset, length]``
  plus the segment name, published atomically (temp + ``os.replace``).
  Readers reload it only when its ``stat`` changes (one ~1µs stat per
  lookup) and remap the segment only when an entry points past the
  currently-mapped size.  Reads take NO file lock ever: the atomic
  rename is the publication barrier.
* **generation** — a fingerprint of everything that could silently
  change what a cached body means (model_version, the serve format
  version, the tuned-overlay directory state) baked into the file
  names: a model bump orphans the old files instead of serving stale
  bytes.  Init best-effort unlinks other generations.
* **quota** — when an append would push the segment past
  ``quota_bytes``, the writer rotates to a fresh epoch segment with an
  empty index (an epoch flush, not an LRU: hot entries repopulate in
  one request each, and whole-file reclaim is the only operation that
  cannot fragment an append-only file).

Returned values are :class:`memoryview` slices of the mapping — the
HTTP layer writes them to the socket without an intermediate copy.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import mmap
import os
import threading
from pathlib import Path

__all__ = ["HotResponseCache", "hot_generation"]

#: segment size ceiling by default — warm response bodies are ~10 KB, so
#: this holds thousands of distinct hot requests before an epoch flush
DEFAULT_QUOTA_BYTES = 64 * 1024 * 1024

#: a single body larger than this fraction of the quota never publishes
#: (one pathological response must not monopolize the segment)
MAX_ENTRY_FRACTION = 8


def _append_segment(seg_path: Path, body: bytes) -> int:
    """Append one body to the segment, fsync'd; returns its offset
    (the injection seam the ENOSPC regression tests monkeypatch)."""
    with open(seg_path, "ab") as seg:
        offset = seg.tell()
        seg.write(body)
        seg.flush()
        os.fsync(seg.fileno())
    return offset


def hot_generation(model_version: str, format_version: int) -> str:
    """The cache generation fingerprint: everything that could change
    what a cached response body MEANS without changing the request
    body.  The tuned-overlay directory joins because ``tuned: true``
    requests compose whatever flags files are on disk at serve time —
    a refreshed fit must orphan responses priced under the old one."""
    parts = [str(model_version), str(int(format_version))]
    tuned_dir = os.environ.get("TPUSIM_TUNED_DIR")
    if tuned_dir:
        try:
            entries = []
            for p in sorted(Path(tuned_dir).glob("*.flags")):
                st = p.stat()
                entries.append(f"{p.name}:{st.st_size}:{st.st_mtime_ns}")
            parts.append(";".join(entries))
        except OSError:
            parts.append("unreadable")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


class HotResponseCache:
    """One hot-response store under ``directory``, shared by every
    acceptor process that mounts the same path with the same
    generation."""

    def __init__(
        self,
        directory: str | Path,
        generation: str,
        quota_bytes: int = DEFAULT_QUOTA_BYTES,
    ):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.generation = str(generation)
        self.quota_bytes = max(int(quota_bytes), 1 << 16)
        self._lock_path = self.dir / "lock"
        self._idx_path = self.dir / f"index-{self.generation}.json"
        # reader state (in-process only; cross-process readers each hold
        # their own and converge via the index stat)
        self._mu = threading.Lock()
        self._entries: dict[str, tuple[int, int]] = {}
        self._segment: str | None = None
        self._idx_stat: tuple[int, int] | None = None
        self._mm: mmap.mmap | None = None
        self._mm_size = 0
        self._mm_segment: str | None = None
        # counters (mirrored on /metrics as serve_hot_*)
        self.hits = 0
        self.misses = 0
        self.publishes = 0
        self.rotations = 0
        # ENOSPC/EIO graceful degradation: a medium-level failure on
        # the publish path disables further publishes for this
        # instance (one warning ever); the read path keeps serving
        # whatever the index already names
        self._publish_disabled = False
        self._reap_other_generations()

    # -- maintenance ---------------------------------------------------------

    def _reap_other_generations(self) -> None:
        """Best-effort unlink of files from older generations — a model
        bump must not leave the previous model's responses on disk
        forever.  Racing peers converge: a lost unlink race is a no-op."""
        for p in self.dir.glob("seg-*.dat"):
            if not p.name.startswith(f"seg-{self.generation}"):
                try:
                    p.unlink()
                except OSError:
                    pass
        for p in self.dir.glob("index-*.json"):
            if p != self._idx_path:
                try:
                    p.unlink()
                except OSError:
                    pass

    # -- write path ----------------------------------------------------------

    def publish(self, key: str, body: bytes) -> bool:
        """Publish one final response body under ``key``.  Serialized
        across processes by an ``flock``; a key a peer already published
        is left alone (first writer wins — both produced byte-identical
        bodies by the serving contract).  Returns True when this call
        made the entry visible."""
        body = bytes(body)
        if self._publish_disabled:
            return False
        if len(body) > self.quota_bytes // MAX_ENTRY_FRACTION:
            return False
        try:
            with open(self._lock_path, "a+b") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                doc = self._read_index_doc()
                entries = doc.get("entries", {})
                if key in entries:
                    return False
                segment = doc.get("segment") \
                    or f"seg-{self.generation}.dat"
                seg_path = self.dir / segment
                size = seg_path.stat().st_size if seg_path.exists() else 0
                if size + len(body) > self.quota_bytes:
                    # epoch flush: a fresh segment + empty index.
                    # Readers follow the index's segment name; the
                    # orphaned file is unlinked (their open mmaps stay
                    # valid until replaced)
                    self.rotations += 1
                    epoch = int(doc.get("epoch", 0)) + 1
                    try:
                        seg_path.unlink()
                    except OSError:
                        pass
                    segment = f"seg-{self.generation}-{epoch}.dat"
                    seg_path = self.dir / segment
                    entries = {}
                    doc["epoch"] = epoch
                    size = 0
                offset = _append_segment(seg_path, body)
                entries[key] = [offset, len(body)]
                doc.update({
                    "format": 1,
                    "generation": self.generation,
                    "segment": segment,
                    "entries": entries,
                })
                tmp = self._idx_path.with_suffix(f".tmp.{os.getpid()}")
                tmp.write_text(json.dumps(doc, sort_keys=True))
                os.replace(tmp, self._idx_path)
        except OSError as e:
            from tpusim.perf.cache import fatal_write_disable

            if fatal_write_disable(
                e,
                f"tpusim.serve: hot-response publish failed under "
                f"{self.dir} ({e}); disabling further hot "
                f"publishes for this instance (reads continue)",
            ):
                self._publish_disabled = True
                return False
            raise  # transient: the daemon counts it and carries on
        self.publishes += 1
        return True

    def _read_index_doc(self) -> dict:
        try:
            doc = json.loads(self._idx_path.read_text())
        except (OSError, json.JSONDecodeError):
            return {}
        if doc.get("generation") != self.generation:
            return {}
        return doc

    # -- read path -----------------------------------------------------------

    def _refresh_index(self) -> None:
        """Reload the index iff its stat moved (caller holds _mu)."""
        try:
            st = self._idx_path.stat()
            stat_sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._entries, self._segment, self._idx_stat = {}, None, None
            return
        if stat_sig == self._idx_stat:
            return
        doc = self._read_index_doc()
        self._entries = {
            k: (int(v[0]), int(v[1]))
            for k, v in (doc.get("entries") or {}).items()
        }
        self._segment = doc.get("segment")
        self._idx_stat = stat_sig

    def _map_for(self, offset: int, length: int) -> mmap.mmap | None:
        """The segment mapping, remapped when the entry points past the
        current map (the segment grew) or the segment rotated (caller
        holds _mu).  Old maps are dropped, never closed — outstanding
        memoryviews keep them alive until the last reader finishes."""
        need = offset + length
        if (
            self._mm is not None
            and self._mm_segment == self._segment
            and self._mm_size >= need
        ):
            return self._mm
        if self._segment is None:
            return None
        try:
            with open(self.dir / self._segment, "rb") as f:
                size = os.fstat(f.fileno()).st_size
                if size < need:
                    return None  # index ahead of visible data: miss
                self._mm = mmap.mmap(
                    f.fileno(), size, prot=mmap.PROT_READ,
                )
                self._mm_size = size
                self._mm_segment = self._segment
        except (OSError, ValueError):
            return None
        return self._mm

    def get(self, key: str) -> memoryview | None:
        """The published body for ``key``, or None.  Lock-free across
        processes: one stat, at most one index reload, a slice of the
        mapping — no flock, no pickling, no dispatch."""
        with self._mu:
            self._refresh_index()
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            offset, length = entry
            mm = self._map_for(offset, length)
            if mm is None:
                self.misses += 1
                return None
            self.hits += 1
            return memoryview(mm)[offset:offset + length]

    def __contains__(self, key: str) -> bool:
        with self._mu:
            self._refresh_index()
            return key in self._entries

    # -- reporting -----------------------------------------------------------

    def stats_dict(self) -> dict[str, float]:
        with self._mu:
            seg_bytes = 0
            if self._segment is not None:
                try:
                    seg_bytes = (self.dir / self._segment).stat().st_size
                except OSError:
                    pass
            return {
                "hot_hits_total": float(self.hits),
                "hot_misses_total": float(self.misses),
                "hot_publishes_total": float(self.publishes),
                "hot_rotations_total": float(self.rotations),
                "hot_entries": float(len(self._entries)),
                "hot_segment_bytes": float(seg_bytes),
            }
