"""Trace registry — the daemon's hot-module layer.

A one-shot ``simulate`` pays trace load (disk read + HLO parse) on every
invocation; the service pays it once per trace and keeps the parsed
:class:`~tpusim.ir.PodTrace` hot for every later request.  Two kinds of
entry:

* **named traces** — subdirectories of ``--trace-root`` (the only
  filesystem the service will read; request bodies cannot name arbitrary
  paths), loaded lazily on first reference and kept for the process
  lifetime.  The trace-level static-analysis diagnostics (``TLxxx``,
  :mod:`tpusim.analysis.trace_passes`) are computed once per entry and
  cached beside the pod — per-request validation then only re-runs the
  cheap config/schedule passes;
* **inline HLO** — request bodies may carry raw HLO module text; the
  parsed single-module pod is cached under the text's content hash, so a
  repeated inline request parses nothing.  The same hash is stamped as
  ``meta["content_hash"]``, which is exactly the module-fingerprint slot
  the :mod:`tpusim.perf` result cache keys on — an inline module's priced
  result is as cacheable as a stored trace's.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from pathlib import Path

from tpusim.ir import CommandKind, PodTrace, TraceCommand

__all__ = ["RegistryEntry", "TraceRegistry", "UnknownTrace"]

#: inline pods kept hot (each is one parsed module; bounded so a client
#: streaming unique programs cannot grow the process without limit)
MAX_INLINE_ENTRIES = 64


class UnknownTrace(KeyError):
    """The request named a trace the registry does not serve."""


@dataclass
class RegistryEntry:
    """One hot trace: the parsed pod + its cached trace diagnostics."""

    name: str
    pod: PodTrace
    #: trace-pass Diagnostics (None until first computed)
    trace_diags: object | None = None
    _lock: threading.Lock = field(default_factory=threading.Lock)


class TraceRegistry:
    """Named trace dirs under one root + content-addressed inline pods."""

    def __init__(self, trace_root: str | Path | None = None):
        self.trace_root = Path(trace_root) if trace_root else None
        self._entries: dict[str, RegistryEntry] = {}
        self._inline: dict[str, RegistryEntry] = {}
        self._lock = threading.Lock()
        # single-flight gate per trace name: a thundering herd on a
        # cold daemon (serve v2 boots N workers that all field their
        # first request at once) parses each trace ONCE per process,
        # not once per thread — parse is seconds for large pods
        self._loading: dict[str, threading.Lock] = {}

    # -- named traces --------------------------------------------------------

    def names(self) -> list[str]:
        """Every servable trace name (a subdir holding meta.json or
        modules/ counts; the registry does not eagerly load them)."""
        if self.trace_root is None or not self.trace_root.is_dir():
            return []
        out = []
        for p in sorted(self.trace_root.iterdir()):
            if p.is_dir() and (
                (p / "meta.json").exists() or (p / "modules").is_dir()
            ):
                out.append(p.name)
        return out

    def get(self, name: str) -> RegistryEntry:
        """The hot entry for ``name``, loading it on first reference.

        Only plain child names of the trace root resolve — path
        separators and ``..`` are rejected so a request body can never
        walk the daemon's filesystem."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is not None:
            return entry
        if self.trace_root is None:
            raise UnknownTrace(
                "this server has no --trace-root; only inline hlo_text "
                "requests are servable"
            )
        if not name or "/" in name or "\\" in name or name in (".", ".."):
            raise UnknownTrace(f"invalid trace name {name!r}")
        path = self.trace_root / name
        if not path.is_dir():
            raise UnknownTrace(
                f"unknown trace {name!r} (known: {self.names()})"
            )
        from tpusim.trace.format import load_trace

        # single-flight: the first thread to reach a cold name parses
        # it; racers block on the per-name gate and then read the hot
        # entry instead of re-parsing the same pod concurrently
        with self._lock:
            gate = self._loading.setdefault(name, threading.Lock())
        with gate:
            with self._lock:
                entry = self._entries.get(name)
            if entry is None:
                pod = load_trace(path)
                with self._lock:
                    entry = self._entries.setdefault(
                        name, RegistryEntry(name=name, pod=pod)
                    )
                    self._loading.pop(name, None)
        return entry

    def trace_diagnostics(self, entry: RegistryEntry):
        """Trace-pass diagnostics for a named entry, computed once.

        Mirrors the ``--validate`` pre-flight's trace half
        (:func:`tpusim.analysis.trace_passes.run_trace_passes` over the
        line-anchored re-walk); config/schedule passes are per-request
        and run in the worker."""
        with entry._lock:
            if entry.trace_diags is None:
                from tpusim.analysis.diagnostics import Diagnostics
                from tpusim.analysis.trace_passes import (
                    load_parsed_trace, run_trace_passes,
                )

                diags = Diagnostics()
                run_trace_passes(
                    load_parsed_trace(self.trace_root / entry.name),
                    diags, lenient=True,
                )
                entry.trace_diags = diags
            return entry.trace_diags

    # -- inline HLO ----------------------------------------------------------

    def get_inline(self, hlo_text: str, num_devices: int = 1) -> RegistryEntry:
        """A single-module pod built from raw HLO text, cached under its
        content hash (keyed with ``num_devices``: the same program on a
        different pod size is a different replay).  Parse errors
        propagate as ``ValueError`` — the HTTP layer maps them to 400."""
        digest = hashlib.sha256(hlo_text.encode()).hexdigest()[:24]
        key = f"{digest}|n{int(num_devices)}"
        with self._lock:
            entry = self._inline.get(key)
        if entry is not None:
            return entry
        from tpusim.trace.native import parse_hlo_module_fast

        mod = parse_hlo_module_fast(hlo_text, name_hint="inline")
        if not mod.computations:
            # the lenient scanners skip lines they cannot read; text
            # that yields NO program is a client error, not a pod
            raise ValueError("no HLO computations parsed from hlo_text")
        # the text hash doubles as the perf-cache module fingerprint —
        # same slot load_trace stamps from the on-disk bytes
        mod.meta.setdefault("content_hash", digest)
        pod = PodTrace(meta={"num_devices": int(num_devices)})
        pod.modules["inline"] = mod
        # one launch per device, mirroring load_trace's
        # modules-without-commandlist path at pod scale
        for dev in range(max(int(num_devices), 1)):
            pod.device(dev).commands.append(
                TraceCommand(
                    kind=CommandKind.KERNEL_LAUNCH, module="inline",
                    device_id=dev,
                )
            )
        entry = RegistryEntry(name=f"inline:{digest}", pod=pod)
        with self._lock:
            self._inline.setdefault(key, entry)
            while len(self._inline) > MAX_INLINE_ENTRIES:
                self._inline.pop(next(iter(self._inline)))
            entry = self._inline[key] if key in self._inline else entry
        return entry

    # -- reporting -----------------------------------------------------------

    def stats_dict(self) -> dict[str, float]:
        with self._lock:
            return {
                "traces_hot": len(self._entries),
                "inline_hot": len(self._inline),
            }
