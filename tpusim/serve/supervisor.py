"""Supervised pre-forked worker pool — the serve v2 robustness core.

PR 5's daemon priced every request under one Python process: one GIL
capped throughput at a single core, and — worse — one bad request (an
OOM on a pathological inline module, a segfault in the native pricing
``.so``, a hung native call) took the whole daemon and every in-flight
job with it.  The reference framework's production analog supervises a
fleet of independent simulation processes; this module gives the serve
tier the same property: **one bad request costs exactly one worker,
never the service**.

Shape: N long-lived worker processes (forked up front, spawn fallback —
the :mod:`tpusim.perf.pool` start-method story), each running
:func:`tpusim.serve.worker.worker_child_main`: its own
:class:`~tpusim.serve.registry.TraceRegistry` (per-worker hot pods), its
own in-memory L1 :class:`~tpusim.perf.ResultCache`, and — when the
daemon mounts ``--result-cache`` — the shared **disk** tier as L2
(``durable=True``: fsync-before-replace, so a worker killed mid-publish
can never leave a short-read record).  Requests travel over a duplex
pipe per worker; responses are the exact dicts the in-process
:class:`~tpusim.serve.worker.ServeWorker` returns, so served stats docs
stay **byte-identical** across 1..N workers and the single-process path.

The supervisor is the policy layer:

* **content-hash affinity** — a request's canonical body hash picks its
  home worker, so identical requests from many users land on a warm L1;
  dispatch stays work-conserving (a busy home spills to any free live
  worker rather than queueing behind itself);
* **per-request deadlines** — a worker that has not answered by the
  request's deadline is killed (SIGTERM, then SIGKILL escalation after a
  short grace) and restarted; a hung native call can no longer pin the
  daemon.  The request gets the 504 it already had a contract for;
* **crash detection + supervised restart** — a worker death (EOF on the
  pipe, a reaped pid) schedules a restart with exponential backoff and
  deterministic jitter (procman-style), so a crash-looping worker cannot
  busy-spin the host;
* **poison-request quarantine** — a request whose worker dies under it
  is retried once on a fresh worker; a second death quarantines the
  request's content hash and answers 422 with a diagnostic.  Later
  identical requests are refused immediately — the pool never
  crash-loops on one input;
* **graceful degradation** — when live workers fall below ``min_live``
  the pool sheds load (:class:`~tpusim.serve.admission.Degraded` → 503 +
  ``Retry-After``) instead of queueing into a dead pool, and
  ``/healthz`` + ``/metrics`` expose per-worker state
  (alive/restarts/kills/quarantine size) so balancers and operators see
  the same truth.
"""

from __future__ import annotations

import hashlib
import json
import multiprocessing
import os
import signal
import threading
import time

from tpusim.serve.admission import Degraded, DeadlineExceeded
from tpusim.serve.worker import RequestError, worker_child_main

__all__ = ["CooperativeCancel", "Supervisor", "WorkerSlot", "WorkerTimeout"]

#: fields stripped from the affinity/quarantine hash: they change how
#: long a request may run, never what it prices (a poison request with a
#: different deadline is the same poison; ``_budget_s`` is the shipped
#: remaining-deadline budget of the cooperative-cancellation frame)
_VOLATILE_BODY_KEYS = ("deadline_ms", "_budget_s", "_trace_ctx")

#: restart backoff ceiling — a flapping worker must not sleep forever
MAX_RESTART_BACKOFF_S = 30.0

#: grace between SIGTERM and the SIGKILL escalation on a deadline kill
KILL_GRACE_S = 0.5

#: grace PAST the request deadline for the worker's cooperative
#: cancellation frame (tpusim.guard): the child's own CancelToken trips
#: at the same instant, and letting it unwind in-process keeps the
#: worker — and its warm registry/L1 — alive.  Only a worker that never
#: reaches a cancel check inside this window (a hung native call, a
#: chaos sleep) meets the SIGTERM/SIGKILL escalation.
COOP_CANCEL_GRACE_S = 0.75


class WorkerTimeout(DeadlineExceeded):
    """The request's deadline expired while a worker was pricing it; the
    worker was killed and is being restarted.  Subclasses
    :class:`DeadlineExceeded` so the HTTP layer's 504 mapping applies."""


class CooperativeCancel(DeadlineExceeded):
    """The request's deadline expired and the worker cancelled it
    IN-PROCESS (tpusim.guard): still a 504, but the worker survives
    with its caches warm and the restart counters untouched."""


class _WorkerGone(ConnectionError):
    """The worker died before it ever STARTED the request — the send
    failed, or the pipe closed before the worker's ack frame came back
    (the request sat unread in the buffer of a worker something else
    killed).  Distinct from a mid-pricing death: this request cannot be
    the killer, so it must not charge the poison-retry budget."""


def _det_jitter(index: int, spawns: int, base: float) -> float:
    """Deterministic restart jitter (procman-style): up to 25% of the
    backoff, derived from the slot identity — reproducible, but two
    slots crashing together do not restart in lockstep."""
    h = hashlib.sha256(f"{index}:{spawns}".encode()).digest()
    return 0.25 * base * (int.from_bytes(h[:4], "big") / 0xFFFFFFFF)


class WorkerSlot:
    """One supervised worker position: the live process (when alive),
    its pipe, and the slot's supervision history.  ``lock`` serializes
    dispatch — a worker prices one request at a time."""

    def __init__(self, index: int):
        self.index = index
        self.lock = threading.Lock()
        self.proc = None
        self.conn = None
        self.alive = False
        self.busy = False
        self.pid: int | None = None
        self.spawns = 0           # spawn ATTEMPTS (the jitter stream)
        self.boots = 0            # successful ready registrations
        self.kills = 0            # deadline kills (supervisor-initiated)
        self.crashes = 0          # uncommanded deaths (request or idle)
        self.consecutive_failures = 0
        self.next_restart_at = 0.0   # time.monotonic() gate
        self.started_at = 0.0
        self.requests_done = 0

    @property
    def restarts(self) -> int:
        # counted from BOOTS, not attempts: a respawn that never came
        # up is not a heal, and the chaos smoke's ">= 1 restart" gate
        # must mean a worker actually returned to service
        return max(self.boots - 1, 0)

    def to_doc(self) -> dict:
        return {
            "index": self.index,
            "alive": self.alive,
            "busy": self.busy,
            "pid": self.pid,
            "restarts": self.restarts,
            "kills": self.kills,
            "crashes": self.crashes,
            "requests_done": self.requests_done,
        }


class Supervisor:
    """Owns the worker fleet; see the module docstring for the policy.

    ``settings`` is the picklable child bootstrap document
    (:func:`~tpusim.serve.worker.worker_child_main`'s contract):
    ``trace_root``, ``disk_cache_dir``, ``cache_entries``,
    ``chaos_hooks``, ``inherited_fds``."""

    def __init__(
        self,
        settings: dict,
        num_workers: int = 2,
        min_live: int = 1,
        retry_budget: int = 1,
        quarantine_max: int = 256,
        restart_backoff_s: float = 0.05,
        spawn_timeout_s: float = 60.0,
        max_worker_rss_bytes: int | None = None,
        quarantine_dir=None,
    ):
        self.settings = dict(settings)
        # serve v3: a shared quarantine directory makes poison refusal
        # FLEET-wide — every acceptor's supervisor publishes its poison
        # verdicts as one atomic file per content hash, so a request
        # that killed workers behind acceptor A is refused immediately
        # by acceptor B instead of being allowed to kill B's workers too
        from pathlib import Path

        self.quarantine_dir = Path(quarantine_dir) if quarantine_dir else None
        if self.quarantine_dir is not None:
            self.quarantine_dir.mkdir(parents=True, exist_ok=True)
        # negative-lookup cache for the shared dir: clean keys are the
        # overwhelming majority, and an open()+ENOENT per dispatch
        # forever would tax the hot path.  The dir's mtime moves when a
        # peer publishes a verdict (rename into the dir), which is the
        # invalidation signal.
        self._quarantine_neg: set[str] = set()
        self._quarantine_dir_mtime: int = -1
        # tpusim.guard: per-worker RSS cap.  The monitor samples each
        # idle worker's /proc RSS about once a second and restarts an
        # over-budget one DELIBERATELY between requests (commanded kill,
        # base restart delay) — the OOM-killer stops choosing victims.
        self.max_worker_rss_bytes = (
            int(max_worker_rss_bytes) if max_worker_rss_bytes else None
        )
        self.num_workers = max(int(num_workers), 1)
        self.min_live = min(max(int(min_live), 1), self.num_workers)
        self.retry_budget = max(int(retry_budget), 0)
        self.quarantine_max = max(int(quarantine_max), 1)
        self.restart_backoff_s = max(float(restart_backoff_s), 0.0)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.slots = [WorkerSlot(i) for i in range(self.num_workers)]
        self._lock = threading.Lock()
        # dispatchers wait HERE for capacity, not on any one worker's
        # lock: any release/restart notifies, every waiter re-scans the
        # whole fleet — a freed neighbor is claimed in microseconds
        # instead of after a per-slot wait timeout
        self._free_cond = threading.Condition()
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._req_seq = 0
        # quarantine: affinity hash -> diagnostic doc (insertion-ordered
        # dict doubles as the LRU)
        self._quarantine: dict[str, dict] = {}
        # cumulative policy counters (mirrored on /metrics as serve_*)
        self.dispatched = 0
        self.retried = 0
        self.shed = 0
        self.poisoned = 0
        self.coop_cancels = 0
        self.rss_kills = 0
        self._rss_tick = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Supervisor":
        """Fork the initial fleet and start the monitor.  Heavy modules
        are imported *first* so every fork inherits them — the child
        never runs the import machinery (forking a threaded parent
        mid-import is the classic deadlock), and a restarted worker is
        ready in milliseconds."""
        self._preload()
        from tpusim.perf.pool import DeferSignals

        # the pool.py discipline: a SIGTERM landing mid-fork is deferred
        # until every child is up and registered, so the drain path can
        # reap them instead of leaving orphans
        with DeferSignals():
            for slot in self.slots:
                self._spawn(slot)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tpusim-serve-supervisor",
            daemon=True,
        )
        self._monitor.start()
        return self

    @staticmethod
    def _preload() -> None:
        import tpusim.analysis.config_passes  # noqa: F401
        import tpusim.faults  # noqa: F401
        import tpusim.sim.driver  # noqa: F401
        import tpusim.timing.config  # noqa: F401
        import tpusim.trace.format  # noqa: F401
        import tpusim.trace.native  # noqa: F401

    def stop(self, grace_s: float = 2.0) -> None:
        """Shut the fleet down: a shutdown sentinel to every live
        worker, a bounded join, SIGKILL for stragglers."""
        with self._lock:
            # same lock _spawn registers under: a restart in flight
            # either registered already (this sweep reaps it) or will
            # see _stop at registration and tear its worker down —
            # no process can slip in AFTER the sweep
            self._stop.set()
        for slot in self.slots:
            conn, proc = slot.conn, slot.proc
            slot.alive = False
            if conn is not None:
                try:
                    conn.send(None)
                except (BrokenPipeError, OSError):
                    pass
        deadline = time.monotonic() + max(grace_s, 0.1)
        for slot in self.slots:
            proc = slot.proc
            if proc is None:
                continue
            proc.join(max(deadline - time.monotonic(), 0.05))
            if proc.is_alive():
                proc.kill()
                proc.join(1.0)
            self._close_slot(slot)
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)

    def _close_slot(self, slot: WorkerSlot) -> None:
        if slot.conn is not None:
            try:
                slot.conn.close()
            except OSError:
                pass
        slot.conn = None
        slot.proc = None
        slot.alive = False
        slot.busy = False
        slot.pid = None

    # -- spawning / supervision ----------------------------------------------

    def _spawn(self, slot: WorkerSlot) -> bool:
        """Start one worker and wait for its ready handshake.  Returns
        False (and schedules a backed-off retry) when the child never
        reported ready."""
        methods = multiprocessing.get_all_start_methods()
        method = "fork" if "fork" in methods else "spawn"
        ctx = multiprocessing.get_context(method)
        settings = self.settings
        if method != "fork":
            # a spawned child inherits none of the parent's fds;
            # inherited_fds carries PARENT fd numbers (the listener),
            # and closing those numbers in a fresh interpreter would
            # hit the child's own pipe/interpreter fds
            settings = {
                k: v for k, v in settings.items() if k != "inherited_fds"
            }
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        proc = ctx.Process(
            target=worker_child_main,
            args=(slot.index, child_conn, settings),
            name=f"tpusim-serve-worker-{slot.index}",
            daemon=True,
        )
        slot.spawns += 1
        try:
            proc.start()
        except OSError:
            parent_conn.close()
            child_conn.close()
            self._mark_failed_spawn(slot)
            return False
        child_conn.close()
        ready = False
        try:
            if parent_conn.poll(self.spawn_timeout_s):
                msg = parent_conn.recv()
                ready = (
                    isinstance(msg, tuple) and len(msg) == 2
                    and msg[0] == "ready"
                )
        except (EOFError, OSError):
            ready = False
        if not ready:
            try:
                proc.kill()
                proc.join(1.0)
            except (OSError, ValueError):
                pass
            parent_conn.close()
            self._mark_failed_spawn(slot)
            return False
        with self._lock:
            if self._stop.is_set():
                registered = False
            else:
                slot.proc = proc
                slot.conn = parent_conn
                slot.pid = proc.pid
                slot.alive = True
                slot.boots += 1
                slot.started_at = time.monotonic()
                registered = True
        if not registered:
            # stop() won the lock first: its sweep is over, so this
            # fresh worker would never receive the shutdown sentinel —
            # tear it down here instead of leaking the process
            try:
                parent_conn.send(None)
            except (BrokenPipeError, OSError):
                pass
            proc.join(1.0)
            if proc.is_alive():
                proc.kill()
                proc.join(1.0)
            parent_conn.close()
            return False
        with self._free_cond:
            self._free_cond.notify_all()  # fresh capacity: wake waiters
        return True

    def _mark_failed_spawn(self, slot: WorkerSlot) -> None:
        with self._lock:
            slot.alive = False
            slot.consecutive_failures += 1
            slot.next_restart_at = (
                time.monotonic() + self._backoff_for(slot)
            )

    def _backoff_for(self, slot: WorkerSlot) -> float:
        base = self.restart_backoff_s * (
            2.0 ** max(slot.consecutive_failures - 1, 0)
        )
        base = min(base, MAX_RESTART_BACKOFF_S)
        return base + _det_jitter(slot.index, slot.spawns, base)

    def _mark_dead(
        self, slot: WorkerSlot, *, commanded: bool, count_kill: bool = True,
    ) -> None:
        """Record a worker death and schedule its restart.  Commanded
        kills (deadline enforcement) restart on the base delay — the
        request was at fault; uncommanded crashes compound the backoff.
        ``count_kill=False`` is the deliberate RSS recycle: commanded
        semantics (base delay, no crash streak) without inflating the
        deadline-kill counter."""
        with self._lock:
            was_alive = slot.alive
            slot.alive = False
            slot.pid = None
            if not was_alive:
                return
            if commanded:
                if count_kill:
                    slot.kills += 1
                slot.next_restart_at = (
                    time.monotonic() + self.restart_backoff_s
                )
            else:
                slot.crashes += 1
                slot.consecutive_failures += 1
                slot.next_restart_at = (
                    time.monotonic() + self._backoff_for(slot)
                )
        proc, conn = slot.proc, slot.conn
        if proc is not None:
            try:
                proc.join(0.1)
            except (OSError, ValueError):
                pass
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        slot.conn = None
        slot.proc = None
        with self._free_cond:
            # waiters must re-check the floor (Degraded beats waiting
            # forever for capacity that just died)
            self._free_cond.notify_all()

    def _kill_slot(self, slot: WorkerSlot, count_kill: bool = True) -> None:
        """Deadline enforcement: SIGTERM, a short grace, then SIGKILL.
        A worker stuck in a native call ignores the TERM; the KILL does
        not ask."""
        proc = slot.proc
        if proc is None or proc.pid is None:
            self._mark_dead(slot, commanded=True, count_kill=count_kill)
            return
        try:
            proc.terminate()
            proc.join(KILL_GRACE_S)
            if proc.is_alive():
                proc.kill()
                proc.join(2.0)
        except (OSError, ValueError):
            pass
        self._mark_dead(slot, commanded=True, count_kill=count_kill)

    def _monitor_loop(self) -> None:
        """Detect idle deaths (a worker OOM-killed between requests),
        restart dead slots once their backoff gate opens, and enforce
        the per-worker RSS cap (tpusim.guard)."""
        while not self._stop.wait(0.05):
            self._rss_tick += 1
            if (
                self.max_worker_rss_bytes is not None
                and self._rss_tick % 20 == 0  # ~1s cadence
            ):
                self._enforce_rss_caps()
            for slot in self.slots:
                if self._stop.is_set():
                    return
                proc = slot.proc
                if slot.alive and proc is not None and not proc.is_alive():
                    # died while idle (or the dispatcher has not noticed
                    # yet); only claim it if no request holds the slot —
                    # the dispatcher's EOF path owns the busy case
                    if slot.lock.acquire(blocking=False):
                        try:
                            if (
                                slot.alive and slot.proc is proc
                                and not proc.is_alive()
                            ):
                                self._mark_dead(slot, commanded=False)
                        finally:
                            slot.lock.release()
                elif (
                    not slot.alive
                    and time.monotonic() >= slot.next_restart_at
                    and slot.lock.acquire(blocking=False)
                ):
                    # respawn in a per-slot thread, slot lock handed
                    # over: _spawn blocks up to spawn_timeout_s on the
                    # ready handshake, and one hung boot must not stall
                    # every OTHER dead slot's restart (or idle-death
                    # detection) behind it.  The held lock is what
                    # keeps respawns single-flight per slot.
                    threading.Thread(
                        target=self._respawn_locked, args=(slot,),
                        name=f"tpusim-serve-respawn-{slot.index}",
                        daemon=True,
                    ).start()

    def _enforce_rss_caps(self) -> None:
        """Restart any IDLE worker whose RSS exceeds the cap — a
        deliberate, supervised recycle (base restart delay, fresh
        caches) instead of the OOM-killer picking a victim mid-request.
        Busy slots are skipped: the cap never truncates in-flight work;
        a worker that stays busy is bounded by the request deadline."""
        from tpusim.guard.watchdog import rss_bytes

        for slot in self.slots:
            if not slot.alive or slot.pid is None:
                continue
            rss = rss_bytes(slot.pid)
            if rss <= 0 or rss < self.max_worker_rss_bytes:
                continue
            if not slot.lock.acquire(blocking=False):
                continue  # busy: re-checked next sweep
            try:
                if slot.alive and slot.pid is not None:
                    with self._lock:
                        self.rss_kills += 1
                    self._kill_slot(slot, count_kill=False)
            finally:
                slot.lock.release()

    def _respawn_locked(self, slot: WorkerSlot) -> None:
        """Monitor handed us ``slot.lock`` already held; boot the
        worker and release."""
        try:
            if not slot.alive and not self._stop.is_set():
                self._spawn(slot)
        finally:
            slot.lock.release()

    # -- dispatch ------------------------------------------------------------

    @staticmethod
    def affinity_key(endpoint: str, body: dict) -> str:
        """Canonical content hash of one request — the affinity AND
        quarantine identity.  Inline HLO text rides in the body, so two
        users submitting the same module land on the same warm L1."""
        doc = {
            k: v for k, v in (body or {}).items()
            if k not in _VOLATILE_BODY_KEYS
        }
        payload = json.dumps(
            {"endpoint": endpoint, "body": doc},
            sort_keys=True, default=str,
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:24]

    def alive_count(self) -> int:
        return sum(1 for s in self.slots if s.alive)

    def _shed_retry_after(self) -> float:
        """Hint for the 503: when the soonest dead slot may come back."""
        now = time.monotonic()
        etas = [
            max(s.next_restart_at - now, 0.0)
            for s in self.slots if not s.alive
        ]
        return max(min(etas, default=1.0), 1.0)

    def _release_slot(self, slot: WorkerSlot) -> None:
        slot.busy = False
        slot.lock.release()
        with self._free_cond:
            self._free_cond.notify_all()

    def _acquire_slot(self, key: str, deadline: float | None) -> WorkerSlot:
        """Claim a live worker: the affinity home when free, any free
        live worker otherwise (work-conserving), else wait for ANY
        release and re-scan.  Raises :class:`Degraded` below the live
        floor and :class:`DeadlineExceeded` when the wait outlives the
        request."""
        start = int(key[:8], 16) % len(self.slots)
        order = [
            self.slots[(start + i) % len(self.slots)]
            for i in range(len(self.slots))
        ]
        with self._free_cond:
            while True:
                if self.alive_count() < self.min_live:
                    self.shed += 1
                    raise Degraded(self._shed_retry_after())
                for slot in order:
                    if slot.alive and slot.lock.acquire(blocking=False):
                        if slot.alive:
                            slot.busy = True
                            return slot
                        slot.lock.release()
                timeout = 0.1
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DeadlineExceeded(
                            "deadline expired waiting for a worker"
                        )
                    timeout = min(timeout, remaining)
                self._free_cond.wait(timeout)

    def _round_trip(
        self, slot: WorkerSlot, endpoint: str, body: dict,
        deadline: float | None, trace_ctx: bool = False,
    ) -> tuple[str, object, dict | None]:
        """One request over one worker's pipe.  Returns the worker's
        ``(kind, payload, trace_extras)`` — ``trace_extras`` is the
        optional ``"spans"`` frame a tracing-aware child sends just
        before its final frame (worker-side span timings + cache tier),
        ``None`` otherwise; raises :class:`WorkerTimeout` after killing
        a worker that outlived the deadline, :class:`_WorkerGone` when
        the worker died without ever acking the request (not charged to
        the poison budget), ``ConnectionError`` on a mid-request death
        (the caller's retry/quarantine path)."""
        with self._lock:
            self._req_seq += 1
            req_id = self._req_seq
        conn = slot.conn
        acked = False
        trace_extras: dict | None = None
        if deadline is not None or trace_ctx:
            body = dict(body or {})
        if deadline is not None:
            # ship the remaining budget so the child arms its own
            # CancelToken (tokens never cross pipes); the signal kill
            # below becomes the ESCALATION past the cooperative grace,
            # not the first resort
            body["_budget_s"] = max(deadline - time.monotonic(), 0.0)
        if trace_ctx:
            # volatile marker (stripped from the affinity/quarantine
            # content hash like _budget_s): the child times its tiers
            # and ships them back in an extra "spans" frame
            body["_trace_ctx"] = True
        try:
            conn.send((req_id, endpoint, body))
        except (BrokenPipeError, OSError):
            self._mark_dead(slot, commanded=False)
            raise _WorkerGone("worker died before the request was sent")
        kill_at = (
            deadline + COOP_CANCEL_GRACE_S if deadline is not None
            else None
        )
        while True:
            timeout = 0.5
            if deadline is not None:
                now = time.monotonic()
                if now >= kill_at:
                    self._kill_slot(slot)
                    raise WorkerTimeout(
                        "worker exceeded the request deadline and was "
                        "killed"
                    )
                # past the deadline but inside the grace: keep polling
                # for the worker's in-process 'cancelled' frame
                timeout = min(timeout, max(kill_at - now, 0.01))
            try:
                if conn.poll(timeout):
                    msg = conn.recv()
                    if (
                        isinstance(msg, tuple) and len(msg) == 3
                        and msg[0] == req_id
                    ):
                        if msg[1] == "ack":
                            acked = True  # the worker READ the request
                            continue
                        if msg[1] == "spans":
                            # worker-side span timings ride ahead of
                            # the final frame; stash, keep polling
                            if isinstance(msg[2], dict):
                                trace_extras = msg[2]
                            continue
                        return msg[1], msg[2], trace_extras
                    continue  # stale frame from a pre-kill epoch
            except (EOFError, OSError):
                self._mark_dead(slot, commanded=False)
                if not acked:
                    # no ack frame: the request sat unread in the pipe
                    # buffer when something ELSE killed the worker —
                    # retry elsewhere without charging the poison budget
                    raise _WorkerGone(
                        "worker died before reading the request"
                    )
                raise ConnectionError(
                    "worker died while pricing the request"
                )
            proc = slot.proc
            if proc is not None and not proc.is_alive():
                # belt for exotic hosts where EOF never surfaces; drain
                # any frames already buffered (the ack, even the full
                # response) before deciding what this death means
                if conn.poll(0):
                    continue
                self._mark_dead(slot, commanded=False)
                if not acked:
                    raise _WorkerGone(
                        "worker died before reading the request"
                    )
                raise ConnectionError(
                    "worker died while pricing the request"
                )

    def execute(
        self, endpoint: str, body: dict, deadline: float | None = None,
        reqtrace=None,
    ) -> dict:
        """Price one request through the fleet, applying every policy in
        the module docstring.  Returns the worker's response dict;
        raises :class:`~tpusim.serve.worker.RequestError` (passthrough
        and quarantine), :class:`Degraded`, :class:`WorkerTimeout`, or
        ``RuntimeError`` (the worker survived but the request blew up —
        the HTTP layer's 500 boundary).  ``reqtrace`` (a
        :class:`tpusim.obs.reqtrace.RequestTrace`) opts the child into
        span collection; its timings merge back as ``dispatch/*``
        children over the shared monotonic clock."""
        key = self.affinity_key(endpoint, body)
        with self._lock:
            poison = self._quarantine.get(key)
        if poison is None and self.quarantine_dir is not None:
            poison = self._quarantine_file_get(key)
        with self._lock:
            if poison is None:
                self.dispatched += 1
            else:
                # every quarantine-refused response counts: the gauge's
                # name is poison_422_TOTAL, and an operator watching it
                # must see ongoing poison traffic, not just first blood
                self.poisoned += 1
        if poison is not None:
            raise RequestError(
                422, "poison_request",
                "this request previously killed its worker and is "
                "quarantined",
                extra={"poison": dict(poison)},
            )
        attempts = 0
        while True:
            slot = self._acquire_slot(key, deadline)
            try:
                kind, payload, trace_extras = self._round_trip(
                    slot, endpoint, body, deadline,
                    trace_ctx=reqtrace is not None,
                )
            except _WorkerGone:
                # the worker died without ever STARTING the request (no
                # ack frame came back — an idle death, or an unrelated
                # kill with the request unread in the buffer).  Not
                # this request's fault: take another slot without
                # charging the poison budget.  Bounded, not a spin:
                # every such failure marks its slot dead, so repeats
                # end in Degraded at the live floor.
                with self._lock:
                    self.retried += 1
                continue
            except ConnectionError as e:
                attempts += 1
                if attempts > self.retry_budget:
                    doc = self._quarantine_add(key, endpoint, body, str(e))
                    with self._lock:
                        self.poisoned += 1
                    raise RequestError(
                        422, "poison_request",
                        f"request killed {attempts} worker(s) and is now "
                        f"quarantined",
                        extra={"poison": doc},
                    )
                with self._lock:
                    self.retried += 1
                continue
            else:
                # bookkeeping BEFORE the release (else runs first):
                # once released the slot may belong to another request,
                # and a crash streak it just started must not be wiped
                # by this request's success
                slot.requests_done += 1
                with self._lock:
                    slot.consecutive_failures = 0
            finally:
                self._release_slot(slot)
            if reqtrace is not None and trace_extras is not None:
                reqtrace.add_worker_spans(trace_extras.get("spans") or ())
                tier = trace_extras.get("tier")
                if tier:
                    reqtrace.meta["tier"] = tier
            if kind in ("ok", "ok_bytes"):
                # ok_bytes is the final serialized response body (the
                # worker's serialization IS the parent's, byte for byte)
                return payload
            if kind == "cancelled":
                # the worker cancelled in-process at its deadline and
                # SURVIVED (slot released live above, caches warm, no
                # restart) — SIGKILL never entered the picture
                with self._lock:
                    self.coop_cancels += 1
                raise CooperativeCancel(str(payload))
            if kind == "request_error":
                status, code, detail, extra = payload
                raise RequestError(status, code, detail, extra)
            raise RuntimeError(str(payload))

    def _quarantine_add(
        self, key: str, endpoint: str, body: dict, detail: str,
    ) -> dict:
        doc = {
            "content_hash": key,
            "endpoint": endpoint,
            "trace": body.get("trace") if isinstance(body, dict) else None,
            "detail": detail,
            "worker_deaths": self.retry_budget + 1,
        }
        with self._lock:
            self._quarantine[key] = doc
            while len(self._quarantine) > self.quarantine_max:
                self._quarantine.pop(next(iter(self._quarantine)))
        if self.quarantine_dir is not None:
            # publish fleet-wide: one atomic file per content hash, so
            # every OTHER acceptor's supervisor refuses this request
            # without paying its own worker deaths first
            try:
                path = self.quarantine_dir / f"{key}.json"
                tmp = path.with_suffix(f".tmp.{os.getpid()}")
                tmp.write_text(json.dumps(doc, sort_keys=True))
                # lint-allow: TL352 best-effort poison marker — a lost
                # verdict just re-learns on the next worker death
                os.replace(tmp, path)
            except OSError:
                pass  # local quarantine still holds
        return doc

    def _quarantine_file_get(self, key: str) -> dict | None:
        """A peer acceptor's quarantine verdict for ``key`` (shared
        dir), cached into the local LRU on first sight.  Negative
        results are cached against the dir's mtime (a publish renames a
        file into the dir, moving it) so clean traffic pays one stat,
        not one failed open per dispatch."""
        try:
            dir_mtime = self.quarantine_dir.stat().st_mtime_ns
        except OSError:
            return None
        with self._lock:
            if dir_mtime != self._quarantine_dir_mtime:
                self._quarantine_neg.clear()
                self._quarantine_dir_mtime = dir_mtime
            elif key in self._quarantine_neg:
                return None
        try:
            doc = json.loads(
                (self.quarantine_dir / f"{key}.json").read_text()
            )
        except (OSError, json.JSONDecodeError):
            with self._lock:
                if dir_mtime == self._quarantine_dir_mtime:
                    self._quarantine_neg.add(key)
            return None
        if not isinstance(doc, dict):
            return None
        with self._lock:
            self._quarantine.setdefault(key, doc)
            while len(self._quarantine) > self.quarantine_max:
                self._quarantine.pop(next(iter(self._quarantine)))
        return doc

    # -- test / chaos helpers ------------------------------------------------

    def worker_pids(self) -> list[int | None]:
        return [s.pid for s in self.slots]

    def kill_worker(self, index: int) -> None:
        """SIGKILL one worker outright (chaos testing — the supervisor
        discovers the death exactly as it would a real crash)."""
        pid = self.slots[index].pid
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass

    # -- reporting -----------------------------------------------------------

    def worker_docs(self) -> list[dict]:
        return [s.to_doc() for s in self.slots]

    def stats_dict(self) -> dict[str, float]:
        return {
            "workers_configured": self.num_workers,
            "workers_alive": self.alive_count(),
            "workers_min_live": self.min_live,
            "worker_restarts_total": sum(s.restarts for s in self.slots),
            "worker_kills_total": sum(s.kills for s in self.slots),
            "worker_crashes_total": sum(s.crashes for s in self.slots),
            "worker_requests_total": sum(
                s.requests_done for s in self.slots
            ),
            "worker_dispatched_total": self.dispatched,
            "worker_retries_total": self.retried,
            "quarantine_size": len(self._quarantine),
            "poison_422_total": self.poisoned,
            "shed_503_total": self.shed,
            # tpusim.guard: in-process deadline cancels (worker
            # survived) and deliberate per-worker RSS recycles
            "worker_coop_cancels_total": self.coop_cancels,
            "worker_rss_kills_total": self.rss_kills,
        }
