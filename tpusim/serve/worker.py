"""Request execution — the daemon's worker layer.

One :class:`ServeWorker` is shared by every HTTP thread and every async
job thread.  It owns the two warm stores that make the service faster
than a CLI run:

* the **process-wide result cache** (:class:`tpusim.perf.ResultCache`,
  optionally disk-backed via ``--result-cache``): every request prices
  through :class:`~tpusim.perf.CachedEngine`, so a repeat or near-repeat
  request (same modules, same composed config) is O(lookup) instead of
  an engine walk.  Each request sees the shared store through a
  :class:`_RequestCacheView` that counts hits/misses *per request* —
  the source of the response's ``cache_hit`` field — while the shared
  counters keep feeding ``/metrics``;
* the **composed-config cache**: ``load_config`` reads preset + tuned
  overlay files from disk; the composition is pure, so it is keyed by
  ``(arch, overlays, tuned)`` and reused across requests.

Validation contract (the 400 path): error-level :mod:`tpusim.analysis`
diagnostics reject the request with the full TLxxx list instead of
pricing garbage — trace passes come pre-computed from the registry,
config passes run on the composed config, schedule passes on the fault
schedule, exactly the ``simulate --validate`` set.

Determinism contract: the pricing path is byte-identical to the CLI —
same :class:`~tpusim.sim.driver.SimDriver`, same arch-from-meta
defaulting, same fault binding — so a served stats doc reproduces a
``python -m tpusim simulate`` run float for float (pinned by
``tests/test_serve.py`` and ``ci/check_golden.py --serve-smoke``;
the per-request view's ``cache_hits``/``cache_misses`` accounting keys
are the one addition, same namespace any ``--result-cache`` CLI run
stamps).
"""

from __future__ import annotations

import json
import threading
import time

from tpusim.perf.cache import ResultCache
from tpusim.timing.model_version import model_version

__all__ = ["RequestError", "ServeWorker", "worker_child_main"]

#: hard cap on request deadlines — a client cannot pin a slot forever
MAX_DEADLINE_S = 600.0

#: composed configs kept hot (each is a small frozen dataclass, but the
#: key is request-controlled — a client stepping an overlay float must
#: not grow the daemon without bound; mirrors MAX_INLINE_ENTRIES)
MAX_CONFIG_ENTRIES = 128

#: strict-lint verdict cache bound (one full diagnostics doc per
#: distinct trace content hash — refusal docs are small; clean docs
#: are near-empty)
MAX_LINT_VERDICTS = 512


class RequestError(Exception):
    """A request-level failure with an HTTP status and a stable code.

    ``extra`` merges into the JSON error body (e.g. the diagnostics doc
    on a validation refusal)."""

    def __init__(
        self, status: int, code: str, detail: str,
        extra: dict | None = None,
    ):
        self.status = int(status)
        self.code = code
        self.detail = detail
        self.extra = extra or {}
        super().__init__(f"{status} {code}: {detail}")


class _RequestCacheView(ResultCache):
    """Per-request window onto the shared cache.

    Delegates storage to the shared instance (every request reads and
    feeds the same warm store) but counts hits/misses locally — the
    response's ``cache_hit`` must describe *this* request, and the
    shared cumulative counters cannot be read racelessly around a run.
    The driver stamps this view's ``stats_dict`` under ``cache_*``, so
    served reports carry per-request cache effectiveness."""

    def __init__(self, shared: ResultCache, timed: bool = False):
        super().__init__(disk_dir=None, max_entries=1)
        self._shared = shared
        # request-trace probe accounting: first-probe start + total
        # probe seconds, folded into ONE "cache_probe" span (a replay
        # may probe per segment; per-probe spans would bloat the tree)
        self._timed = timed
        self._probe_t0: float | None = None
        self._probe_s = 0.0

    def get(self, key):
        if self._timed:
            t0 = time.monotonic()
            result = self._shared.get(key)
            if self._probe_t0 is None:
                self._probe_t0 = t0
            self._probe_s += time.monotonic() - t0
        else:
            result = self._shared.get(key)
        if result is not None:
            self.hits += 1
        else:
            self.misses += 1
        return result

    def probe_span(self) -> tuple[str, float, float] | None:
        """The folded ``cache_probe`` span, or None if never probed."""
        if self._probe_t0 is None:
            return None
        return ("cache_probe", self._probe_t0, self._probe_s)

    def put(self, key, result) -> None:
        self._shared.put(key, result)

    def stats_dict(self) -> dict[str, float]:
        return {"hits": self.hits, "misses": self.misses}


def _pod_devices(pod) -> int:
    """The driver's pod-size rule, mirrored exactly (fault schedules
    must bind against the same torus the replay will use)."""
    return max(
        int(pod.meta.get("num_devices", 0) or 0),
        max((m.num_devices for m in pod.modules.values()), default=1),
        len(pod.devices) or 1,
    )


class ServeWorker:
    """Executes simulate / lint / sweep requests over the warm stores."""

    def __init__(
        self,
        registry,
        result_cache: ResultCache | None = None,
        workers: int = 1,
        strict_lint: bool = False,
    ):
        self.registry = registry
        self.result_cache = result_cache
        self.workers = max(int(workers), 1)
        self.strict_lint = bool(strict_lint)
        self.model_version = model_version()
        self._config_cache: dict[str, object] = {}
        self._config_lock = threading.Lock()
        # strict-lint verdict tier: the full trace-pass diagnostics doc
        # per CONTENT HASH — a fleet behind --strict-lint lints each
        # distinct trace exactly once, then refuses (422) or admits
        # from the cached verdict
        self._lint_verdicts: dict[str, dict] = {}
        self._lint_lock = threading.Lock()
        self.strict_lint_refused = 0
        # requests priced by THIS worker object (the serve v3 front
        # smoke's zero-dispatch proof: a pass served entirely from the
        # hot mmap tier must leave this counter untouched)
        self.priced = 0
        # cumulative async-job executor accounting (campaign_* and
        # advise_* namespaces), mirrored on /metrics
        self._job_totals: dict[str, float] = {}
        self._job_lock = threading.Lock()

    # -- shared resolution ---------------------------------------------------

    def _resolve_entry(self, req: dict):
        """The request's pod: a named registry trace or inline HLO."""
        from tpusim.serve.registry import UnknownTrace

        trace = req.get("trace")
        hlo_text = req.get("hlo_text")
        if (trace is None) == (hlo_text is None):
            raise RequestError(
                400, "bad_request",
                "exactly one of 'trace' (registry name) or 'hlo_text' "
                "(inline HLO) is required",
            )
        if trace is not None:
            try:
                return self.registry.get(str(trace)), False
            except UnknownTrace as e:
                raise RequestError(404, "unknown_trace", str(e.args[0]))
        try:
            entry = self.registry.get_inline(
                str(hlo_text), int(req.get("num_devices", 1) or 1)
            )
        except (ValueError, KeyError, TypeError) as e:
            raise RequestError(
                400, "hlo_parse_error",
                f"inline HLO did not parse: {type(e).__name__}: {e}",
            )
        return entry, True

    def _config_for(self, pod, req: dict):
        """The composed SimConfig, cached by (arch, overlays, tuned).

        Request overlays are JSON dicts only — a service request must
        never name files on the daemon's filesystem."""
        from tpusim.timing.config import load_config

        arch = req.get("arch")
        overlays = req.get("overlays") or []
        if not isinstance(overlays, list) or not all(
            isinstance(o, dict) for o in overlays
        ):
            raise RequestError(
                400, "bad_request",
                "'overlays' must be a list of JSON objects "
                "(flag files are not servable)",
            )
        tuned = bool(req.get("tuned", True))
        if arch is None:
            # the CLI's arch-from-capture defaulting, via the same
            # named-preset route so the tuned overlay applies
            kind = str(pod.meta.get("device_kind", "") or "")
            if kind:
                from tpusim.timing.arch import detect_arch

                arch = detect_arch(kind).name
        key = json.dumps(
            {"arch": arch, "overlays": overlays, "tuned": tuned},
            sort_keys=True,
        )
        with self._config_lock:
            cfg = self._config_cache.get(key)
        if cfg is None:
            try:
                cfg = load_config(
                    arch=arch, overlays=list(overlays), tuned=tuned
                )
            except (KeyError, ValueError, FileNotFoundError) as e:
                raise RequestError(
                    400, "bad_config", f"config does not compose: {e}"
                )
            with self._config_lock:
                cfg = self._config_cache.setdefault(key, cfg)
                while len(self._config_cache) > MAX_CONFIG_ENTRIES:
                    oldest = next(iter(self._config_cache))
                    if oldest == key:
                        break
                    self._config_cache.pop(oldest)
        return cfg

    def _analyze(self, entry, inline: bool, cfg, req: dict):
        """The per-request pre-flight: cached trace passes + fresh
        config/schedule/memory passes.  Returns the Diagnostics."""
        from tpusim.analysis.config_passes import run_config_passes
        from tpusim.analysis.diagnostics import Diagnostics
        from tpusim.analysis.memory_passes import run_memory_passes

        diags = Diagnostics()
        if not inline:
            diags.items.extend(
                self.registry.trace_diagnostics(entry).items
            )
        run_config_passes(cfg, diags, trace_meta=entry.pod.meta)
        # TL40x vs the request's composed arch — the dataflow walk is
        # memoized on each module object, so a hot pod pays it once
        run_memory_passes(entry.pod.modules, cfg, diags)
        faults = req.get("faults")
        if faults is not None:
            from tpusim.analysis.schedule_passes import run_schedule_passes
            from tpusim.ici.topology import torus_for

            topo = torus_for(_pod_devices(entry.pod), cfg.arch.name)
            run_schedule_passes(faults, topo, diags)
        return diags

    @staticmethod
    def _reject(diags) -> None:
        raise RequestError(
            400, "validation_failed",
            f"static analysis refused the request: {diags.summary()}",
            extra={
                "codes": sorted(d.code for d in diags.errors),
                "diagnostics": json.loads(diags.to_json()),
            },
        )

    # -- strict-lint gate ----------------------------------------------------

    def _content_key(self, entry, inline: bool, req: dict) -> str:
        """The verdict-cache identity: the modules' content hashes
        plus (registry traces) a commandlist fingerprint — the trace
        passes judge BOTH artifacts, so two traces sharing modules but
        differing commandlists must not cross-serve each other's
        verdict.  The same content re-registered under another name,
        or re-submitted inline, still lints once."""
        hashes = sorted(
            str(m.meta.get("content_hash", "") or "")
            for m in entry.pod.modules.values()
        )
        if not hashes or not any(hashes):
            hashes = [entry.name]  # degenerate: no stamped hash
        if not inline:
            # the trace passes judge THREE artifacts: modules,
            # commandlist.jsonl, and meta.json (TL007/TL010 gate on
            # the meta pod declaration) — the key must cover all of
            # them or look-alike traces cross-serve verdicts
            fp = getattr(entry, "_artifact_fp", None)
            if fp is None:
                import hashlib

                parts = []
                root = getattr(self.registry, "trace_root", None)
                if root is not None:
                    for fname in ("commandlist.jsonl", "meta.json"):
                        p = root / entry.name / fname
                        try:
                            digest = hashlib.sha256(
                                p.read_bytes()
                            ).hexdigest()[:16]
                        except OSError:
                            digest = "absent"
                        parts.append(f"{fname}:{digest}")
                fp = ";".join(parts) or "no-root"
                try:
                    entry._artifact_fp = fp
                except (AttributeError, TypeError):
                    pass
            hashes.append(fp)
        return "|".join(hashes)

    def _strict_lint_gate(self, entry, inline: bool, req: dict) -> None:
        """``--strict-lint``: refuse (422 + the full diagnostics doc)
        any trace whose trace-family passes report errors OR warnings.
        TL5xx perf-lint findings are exempt: they ride along in the
        doc as advisory warnings but never refuse.  The verdict is
        cached by content hash, so a fleet lints each distinct trace
        once; later submissions are admitted or refused from the cache
        without re-walking a line."""
        key = self._content_key(entry, inline, req)
        with self._lint_lock:
            doc = self._lint_verdicts.get(key)
        if doc is None:
            from tpusim.analysis.diagnostics import Diagnostics

            if inline:
                from tpusim.analysis.trace_passes import (
                    _parse_module_lines, run_module_passes,
                )

                diags = Diagnostics()
                pm = _parse_module_lines(
                    entry.name, "<inline hlo>",
                    str(req.get("hlo_text", "")),
                )
                run_module_passes(pm, diags, lenient=True)
            else:
                diags = self.registry.trace_diagnostics(entry)
            doc = json.loads(diags.to_json())
            with self._lint_lock:
                self._lint_verdicts.setdefault(key, doc)
                while len(self._lint_verdicts) > MAX_LINT_VERDICTS:
                    oldest = next(iter(self._lint_verdicts))
                    if oldest == key:
                        break
                    self._lint_verdicts.pop(oldest)
        # TL5xx perf-lint findings are advisory by contract: they pass
        # through in the cached doc for the caller to read but never
        # refuse admission, so recount the gate's severities without
        # them (the counts field keeps the full tally).
        counts: dict = {}
        for d in doc.get("diagnostics", []):
            if str(d.get("code", "")).startswith("TL5"):
                continue
            sev = d.get("severity", "")
            counts[sev] = counts.get(sev, 0) + 1
        if counts.get("error") or counts.get("warning"):
            with self._lint_lock:
                self.strict_lint_refused += 1
            raise RequestError(
                422, "strict_lint_refused",
                f"strict lint refused the trace: "
                f"{counts.get('error', 0)} error(s), "
                f"{counts.get('warning', 0)} warning(s) "
                f"(the daemon runs --strict-lint; see 'diagnostics')",
                extra={"diagnostics": doc},
            )

    # -- endpoints -----------------------------------------------------------

    def simulate(self, req: dict, cancel=None, spans=None) -> dict:
        """``POST /v1/simulate`` — price one pod replay.  ``cancel``
        (a :class:`tpusim.guard.CancelToken` armed with the request's
        deadline) makes the replay cooperatively cancellable: the
        driver raises :class:`tpusim.guard.OperationCancelled` at the
        next command/op boundary, the HTTP layer answers 504, and this
        worker — process or thread — survives with every cache warm.
        ``spans`` (request tracing) collects ``(name, abs_monotonic_s,
        dur_s)`` tier timings — lint verdict, cache probe, pricing."""
        from tpusim.sim.driver import SimDriver

        entry, inline = self._resolve_entry(req)
        cfg = self._config_for(entry.pod, req)
        if self.strict_lint:
            if spans is None:
                self._strict_lint_gate(entry, inline, req)
            else:
                t_lint = time.monotonic()
                try:
                    self._strict_lint_gate(entry, inline, req)
                finally:
                    # a 422 refusal is the interesting trace — record
                    # the verdict span on the way out either way
                    spans.append(
                        ("lint", t_lint, time.monotonic() - t_lint)
                    )
        if bool(req.get("validate", True)):
            diags = self._analyze(entry, inline, cfg, req)
            if diags.has_errors:
                self._reject(diags)
        faults = None
        if req.get("faults") is not None:
            from tpusim.faults import load_fault_schedule

            try:
                faults = load_fault_schedule(req["faults"])
            except (ValueError, KeyError, TypeError) as e:
                raise RequestError(
                    400, "bad_faults", f"fault schedule rejected: {e}"
                )
        view = (
            _RequestCacheView(self.result_cache, timed=spans is not None)
            if self.result_cache is not None else None
        )
        from tpusim.faults import TopologyPartitionedError

        t_price = time.monotonic()
        try:
            report = SimDriver(
                cfg, faults=faults, result_cache=view,
                workers=self.workers, cancel=cancel,
            ).run(entry.pod)
        except (ValueError, KeyError, TopologyPartitionedError) as e:
            # a replay refusal (partitioned topology, unknown module) is
            # the request's fault, not the server's
            raise RequestError(
                422, "replay_failed", f"{type(e).__name__}: {e}"
            )
        finally:
            if spans is not None:
                # price covers the whole driver run (compile rides
                # inside it on a cold module); the folded cache-probe
                # span overlaps it as a child-by-timing
                spans.append(
                    ("price", t_price, time.monotonic() - t_price)
                )
                if view is not None:
                    probe = view.probe_span()
                    if probe is not None:
                        spans.append(probe)
        stats = json.loads(report.stats.to_json())
        self.priced += 1
        return {
            "trace": entry.name,
            "arch": cfg.arch.name,
            "num_devices": report.num_devices,
            "sim_cycles": report.cycles,
            "cache_hit": bool(
                view is not None and view.misses == 0 and view.hits > 0
            ),
            "stats": stats,
        }

    def lint(self, req: dict, cancel=None, spans=None) -> dict:
        """``POST /v1/lint`` — the analyzer's report, never a refusal
        (lint findings are the payload, not an error).  ``cancel`` is
        accepted for endpoint-signature uniformity; analysis runs in
        milliseconds, below any useful cancellation grain.  ``spans``
        (request tracing) collects the analyze timing."""
        entry, inline = self._resolve_entry(req)
        cfg = self._config_for(entry.pod, req)
        t_analyze = time.monotonic()
        diags = self._analyze(entry, inline, cfg, req)
        if spans is not None:
            spans.append(
                ("analyze", t_analyze, time.monotonic() - t_analyze)
            )
        from tpusim.analysis.diagnostics import Severity

        return {
            "trace": entry.name,
            "arch": cfg.arch.name,
            "summary": diags.summary(),
            "errors": diags.count(Severity.ERROR),
            "warnings": diags.count(Severity.WARNING),
            "trace_passes": "skipped (inline hlo)" if inline else "ran",
            "diagnostics": json.loads(diags.to_json()),
        }

    def sweep(self, req: dict, cancel=None) -> dict:
        """``POST /v1/sweep`` body → the sweep report (runs on a job
        thread; the HTTP layer returns a job id immediately).
        ``cancel`` is the job's token — ``DELETE /v1/jobs/<id>`` trips
        it and the sweep unwinds at link grain as ``cancelled``."""
        from tpusim.faults.sweep import single_link_sweep, trace_step_sweep
        from tpusim.ici.topology import torus_for

        if req.get("trace") is not None or req.get("hlo_text") is not None:
            # trace sweeps replay a pod per scenario — a registry name
            # or an inline module both resolve to one
            entry, _ = self._resolve_entry(req)
            cfg = self._config_for(entry.pod, req)
            chips = int(req.get("chips") or _pod_devices(entry.pod))
            topo = torus_for(chips, cfg.arch.name)
            result = trace_step_sweep(
                None, topo,
                max_scenarios=int(req.get("max_scenarios", 16) or 16),
                workers=self.workers,
                result_cache=self.result_cache,
                pod=entry.pod,
                config=cfg,
                cancel=cancel,
            )
        else:
            cfg = self._config_for_sweep(req)
            chips = int(req.get("chips", 64) or 64)
            topo = torus_for(chips, cfg.arch.name)
            payload_mb = float(req.get("payload_mb", 64.0) or 64.0)
            result = single_link_sweep(
                topo, cfg.arch.ici,
                payload_bytes=payload_mb * 1024 * 1024,
                kind=str(req.get("kind", "all-reduce")),
                workers=self.workers,
                cancel=cancel,
            )
        return result.to_doc()

    def campaign(self, req: dict, out_dir=None, cancel=None) -> dict:
        """``POST /v1/campaign`` body → the campaign report (runs on a
        job thread).  ``req['spec']`` is the campaign spec document;
        the workload is the usual ``trace``/``hlo_text`` pair.  With a
        daemon ``--state-dir``, ``out_dir`` points at this job's
        journal directory — a restarted daemon re-enters here and
        resumes from the last completed scenario instead of re-pricing
        from zero."""
        import json as _json

        from tpusim.analysis import ValidationError
        from tpusim.campaign import (
            CampaignSpecError, load_campaign_spec, run_campaign,
        )

        spec_doc = req.get("spec")
        if not isinstance(spec_doc, dict):
            raise RequestError(
                400, "bad_request",
                "'spec' (a campaign spec object) is required",
            )
        try:
            spec = load_campaign_spec(spec_doc)
        except CampaignSpecError as e:
            raise RequestError(
                400, "bad_campaign_spec", str(e),
                extra={"codes": [e.code]},
            )
        entry, _inline = self._resolve_entry(req)
        try:
            result = run_campaign(
                spec,
                pod=entry.pod,
                trace_name=entry.name,
                out_dir=out_dir,
                resume=out_dir is not None,
                result_cache=self.result_cache,
                workers=self.workers,
                cancel=cancel,
            )
        except ValidationError as e:
            raise RequestError(
                400, "validation_failed",
                f"campaign spec refused: {e.diags.summary()}",
                extra={
                    "codes": sorted(d.code for d in e.diags.errors),
                    "diagnostics": _json.loads(e.diags.to_json()),
                },
            )
        self._accumulate(result.stats.stats_dict())
        return result.doc

    def fleet(self, req: dict, out_dir=None, cancel=None) -> dict:
        """``POST /v1/fleet`` body → the fleet digital-twin report
        (runs on a job thread).  ``req['spec']`` is the fleet spec
        document; the workload is the usual ``trace``/``hlo_text``
        pair.  With a daemon ``--state-dir``, ``out_dir`` points at
        this job's journal directory — a restarted daemon re-enters
        here and resumes with zero journaled pricing intervals
        re-priced."""
        import json as _json

        from tpusim.analysis import ValidationError
        from tpusim.fleet import FleetSpecError, load_fleet_spec, run_fleet

        spec_doc = req.get("spec")
        if not isinstance(spec_doc, dict):
            raise RequestError(
                400, "bad_request",
                "'spec' (a fleet spec object) is required",
            )
        try:
            spec = load_fleet_spec(spec_doc)
        except FleetSpecError as e:
            raise RequestError(
                400, "bad_fleet_spec", str(e),
                extra={"codes": [e.code]},
            )
        entry, _inline = self._resolve_entry(req)
        try:
            result = run_fleet(
                spec,
                pod=entry.pod,
                trace_name=entry.name,
                out_dir=out_dir,
                resume=out_dir is not None,
                result_cache=self.result_cache,
                workers=self.workers,
                cancel=cancel,
            )
        except ValidationError as e:
            raise RequestError(
                400, "validation_failed",
                f"fleet spec refused: {e.diags.summary()}",
                extra={
                    "codes": sorted(d.code for d in e.diags.errors),
                    "diagnostics": _json.loads(e.diags.to_json()),
                },
            )
        self._accumulate(result.stats.stats_dict())
        return result.doc

    def advise(self, req: dict, cancel=None) -> dict:
        """``POST /v1/advise`` body → the ranked advisor report (runs
        on a job thread).  ``req['spec']`` is the advise spec document;
        the workload is the usual ``trace``/``hlo_text`` pair.  The
        served doc is byte-identical to the ``tpusim advise`` CLI's —
        cells price through the same shared result cache."""
        import json as _json

        from tpusim.advise import (
            AdviseSpecError, load_advise_spec, run_advise,
        )
        from tpusim.analysis import ValidationError

        spec_doc = req.get("spec")
        if not isinstance(spec_doc, dict):
            raise RequestError(
                400, "bad_request",
                "'spec' (an advise spec object) is required",
            )
        try:
            spec = load_advise_spec(spec_doc)
        except AdviseSpecError as e:
            raise RequestError(
                400, "bad_advise_spec", str(e),
                extra={"codes": [e.code]},
            )
        entry, _inline = self._resolve_entry(req)
        try:
            result = run_advise(
                spec,
                pod=entry.pod,
                trace_name=entry.name,
                result_cache=self.result_cache,
                workers=self.workers,
                cancel=cancel,
            )
        except ValidationError as e:
            raise RequestError(
                400, "validation_failed",
                f"advise spec refused: {e.diags.summary()}",
                extra={
                    "codes": sorted(d.code for d in e.diags.errors),
                    "diagnostics": _json.loads(e.diags.to_json()),
                },
            )
        self._accumulate(result.stats.stats_dict())
        return result.doc

    def _accumulate(self, stats: dict[str, float]) -> None:
        with self._job_lock:
            for k, v in stats.items():
                self._job_totals[k] = self._job_totals.get(k, 0.0) + v

    def _config_for_sweep(self, req: dict):
        """Analytic sweeps have no pod to default the arch from."""

        class _NoPod:
            meta: dict = {}
            modules: dict = {}
            devices: dict = {}

        shim = _NoPod()
        if req.get("arch") is None:
            req = dict(req, arch="v5p")  # the faults CLI default
        return self._config_for(shim, req)

    # -- reporting -----------------------------------------------------------

    def stats_dict(self) -> dict[str, float]:
        out: dict[str, float] = {}
        if self.result_cache is not None:
            for k, v in self.result_cache.stats_dict().items():
                out[f"cache_{k}"] = v
        with self._config_lock:
            out["configs_hot"] = len(self._config_cache)
        out["priced_total"] = self.priced
        if self.strict_lint:
            with self._lint_lock:
                out["lint_verdicts_cached"] = len(self._lint_verdicts)
            out["strict_lint_refused_total"] = self.strict_lint_refused
        with self._job_lock:
            out.update(self._job_totals)
        return out


# ---------------------------------------------------------------------------
# Supervised worker process (serve v2)
# ---------------------------------------------------------------------------

#: endpoints a supervised worker will execute; everything else is a
#: supervisor-side programming error, not client-reachable state
_CHILD_ENDPOINTS = frozenset({"simulate", "lint"})


def worker_child_main(index: int, conn, settings: dict) -> None:
    """Entry point of one supervised worker process.

    ``conn`` is the child end of the supervisor's duplex pipe; the
    protocol is ``(req_id, endpoint, body)`` in, ``(req_id, kind,
    payload)`` out with ``kind`` one of ``ack`` / ``ok`` /
    ``request_error`` / ``error``, and ``None`` as the shutdown
    sentinel.  The ``ack`` frame goes back the instant a request is
    read off the pipe, BEFORE any work: a worker that dies without
    acking provably never started the request, so the supervisor can
    retry it without charging the poison budget — a send() that landed
    in the pipe buffer of a worker the OOM killer then took is not the
    request's fault.  The response
    payloads are the exact objects the in-process :class:`ServeWorker`
    returns/raises — the supervisor re-raises them in the parent, which
    is what keeps multi-worker responses byte-identical to the
    single-process daemon.

    Each worker owns its whole pricing world: a private
    :class:`~tpusim.serve.registry.TraceRegistry` (per-worker hot pods —
    affinity dispatch keeps a trace parsed in ~one worker), a private
    in-memory L1 :class:`~tpusim.perf.ResultCache`, and, when
    ``settings["disk_cache_dir"]`` is set, the shared disk tier as L2
    with ``durable=True`` (fsync-before-replace: a worker killed
    mid-publish can never leave a short-read record for the fleet to
    warn about).  Nothing here is shared mutable state with the parent,
    so a SIGKILL at any instant costs exactly this process.

    Cooperative cancellation (tpusim.guard): the supervisor ships the
    request's remaining deadline budget as the volatile body key
    ``_budget_s``; the child builds its own
    :class:`~tpusim.guard.CancelToken` from it (tokens never travel
    across pipes) and prices under it.  A tripped token unwinds as the
    ``cancelled`` frame — the worker stays alive with its registry and
    L1 warm, the parent answers 504, and SIGTERM/SIGKILL becomes the
    escalation for a worker that never reaches a check (a hung native
    call), not the first resort.

    ``settings["chaos_hooks"]`` arms the fault-injection hooks the chaos
    tests and the CI chaos smoke use (``_chaos_exit`` → ``os._exit``,
    ``_chaos_sleep_s`` → sleep before pricing, ``_chaos_spin_s`` → a
    cancel-aware busy loop standing in for long pricing); a production
    daemon never sets it.
    """
    import os
    import signal as _signal
    import time as _time

    # the parent's handlers (SIGTERM → drain) are wrong here: a worker
    # dies promptly on TERM (the supervisor escalates to KILL anyway)
    # and ignores INT (a terminal ^C must drain via the parent, which
    # reaps the fleet — not race it to death)
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
    _signal.signal(_signal.SIGINT, _signal.SIG_IGN)
    # under fork the child inherits the daemon's listening socket; keep
    # it and a killed daemon's port stays bound by its orphans
    for fd in settings.get("inherited_fds") or ():
        try:
            os.close(int(fd))
        except (OSError, ValueError, TypeError):
            pass

    from tpusim.guard.cancel import OperationCancelled
    from tpusim.perf.cache import ResultCache
    from tpusim.serve.registry import TraceRegistry

    disk_dir = settings.get("disk_cache_dir") or None
    if settings.get("compile_cache_dir"):
        # the durable compiled-module tier, same dir discipline as the
        # shared L2: every worker loads columns a peer compiled and
        # publishes durably (fsync-before-replace) for the fleet
        from tpusim.fastpath.store import as_compile_store

        as_compile_store(
            settings["compile_cache_dir"], durable=True,
            quota_bytes=settings.get("cache_quota_bytes"),
        )
    # pull the request path's one-time costs (numpy import, native
    # dlopen, lazy pricing-stack imports) forward to worker boot
    from tpusim.serve.daemon import _prewarm_pricing_stack

    _prewarm_pricing_stack()
    registry = TraceRegistry(settings.get("trace_root"))
    cache = ResultCache(
        disk_dir=disk_dir,
        max_entries=int(settings.get("cache_entries", 4096) or 4096),
        durable=disk_dir is not None,
        # the daemon's --cache-quota governs every writer of the shared
        # dir: each worker enforces it on its own puts (gc_store deletes
        # are idempotent across the fleet by design)
        quota_bytes=settings.get("cache_quota_bytes"),
    )
    worker = ServeWorker(
        registry, result_cache=cache, workers=1,
        strict_lint=bool(settings.get("strict_lint")),
    )
    chaos = bool(settings.get("chaos_hooks"))
    # the daemon's response format version: when present, success
    # responses travel as the final serialized body bytes (see below)
    format_version = settings.get("format_version")

    try:
        conn.send(("ready", os.getpid()))
    except (BrokenPipeError, OSError):
        return
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        if msg is None:
            return
        req_id, endpoint, body = msg
        try:
            # ack before ANY work (chaos hooks included): death after
            # this frame means the request was in flight when the
            # worker died — the supervisor's poison accounting keys
            # off exactly that distinction
            conn.send((req_id, "ack", None))
        except (BrokenPipeError, OSError):
            return
        cancel = None
        if isinstance(body, dict) and body.get("_budget_s") is not None:
            from tpusim.guard.cancel import CancelToken

            body = dict(body)
            cancel = CancelToken.after(float(body.pop("_budget_s")))
        spans = None
        if isinstance(body, dict) and "_trace_ctx" in body:
            # request tracing is on: time this request's tiers and ship
            # them back in an extra "spans" frame ahead of the final
            # one.  The marker is volatile (stripped from content
            # hashes) and must never reach the endpoint body.
            body = dict(body)
            body.pop("_trace_ctx")
            spans = []
        if chaos and isinstance(body, dict):
            if body.get("_chaos_exit"):
                os._exit(3)
            nap = body.get("_chaos_sleep_s")
            if nap:
                _time.sleep(min(float(nap), 30.0))
        try:
            if chaos and isinstance(body, dict) and body.get("_chaos_spin_s"):
                # a cancel-aware stand-in for long pricing: spins like a
                # big replay would, checking the token at its grain —
                # the deterministic vehicle for the coop-cancel smoke
                spin_until = _time.monotonic() + min(
                    float(body["_chaos_spin_s"]), 30.0
                )
                while _time.monotonic() < spin_until:
                    if cancel is not None:
                        cancel.check()
                    _time.sleep(0.005)
            if endpoint not in _CHILD_ENDPOINTS:
                raise RequestError(
                    404, "unknown_endpoint",
                    f"supervised workers serve {sorted(_CHILD_ENDPOINTS)},"
                    f" not {endpoint!r}",
                )
            result = getattr(worker, endpoint)(
                body, cancel=cancel, spans=spans,
            ) if spans is not None else getattr(worker, endpoint)(
                body, cancel=cancel,
            )
        except RequestError as e:
            out = (req_id, "request_error",
                   (e.status, e.code, e.detail, e.extra))
            tier = None
        except OperationCancelled as e:
            # the deadline tripped INSIDE the pricing stack: this
            # process is healthy, its caches warm — the supervisor
            # answers 504 without killing anything
            out = (req_id, "cancelled", str(e))
            tier = None
        except Exception as e:  # noqa: BLE001 - the worker's 500 boundary
            out = (req_id, "error", f"{type(e).__name__}: {e}")
            tier = None
        else:
            tier = None
            if isinstance(result, dict) and "cache_hit" in result:
                tier = "warm" if result.get("cache_hit") else "priced"
            if format_version is not None:
                # serialize HERE, byte-for-byte what the parent's
                # _send_json would produce (same dumps args, same
                # envelope): the parent then writes the bytes straight
                # to the socket instead of unpickling a ~10 KB stats
                # dict and re-serializing it under its GIL — the hot
                # half of the per-request parent cost
                t_ser = _time.monotonic()
                blob = json.dumps({
                    "format_version": format_version,
                    "model_version": worker.model_version,
                    **result,
                }, sort_keys=True).encode() + b"\n"
                if spans is not None:
                    spans.append(
                        ("serialize", t_ser, _time.monotonic() - t_ser)
                    )
                out = (req_id, "ok_bytes", blob)
            else:
                out = (req_id, "ok", result)
        try:
            if spans is not None:
                # span frame rides ahead of the final frame; the bytes
                # of the final frame are untouched by tracing
                conn.send((req_id, "spans", {"spans": spans, "tier": tier}))
            conn.send(out)
        except (BrokenPipeError, OSError):
            return
