"""Simulation driver layer: command-stream replay + stats reporting.

The rebuild of ``gpu-simulator/main.cc`` (trace-driven driver) and the stats
printing of ``gpgpu_sim::print_stats`` / ``gpgpusim_entrypoint.cc``.
"""

from tpusim.sim.driver import SimDriver, SimReport, simulate_trace
from tpusim.sim.stats import StatsRegistry, EXIT_SENTINEL

__all__ = [
    "SimDriver",
    "SimReport",
    "simulate_trace",
    "StatsRegistry",
    "EXIT_SENTINEL",
]
