"""Interactive single-step debugger.

The rebuild of the reference's gdb-style interactive debug loop
(``src/debug.{h,cc}``: ``gpgpu_debug()`` on ``g_single_step``, with
watchpoints).  Steps through a module's entry schedule one HLO op at a
time, printing each op's cost breakdown; breakpoints match op names or
opcodes (the watchpoint analogue).

Commands::

    s [n]      step n ops (default 1)
    c          continue to next breakpoint / end
    b <pat>    add breakpoint on op name or opcode substring
    l [n]      list the next n ops (default 5)
    p          print current op details (cost, bytes, attrs)
    stats      print accumulated counters so far
    q          quit
"""

from __future__ import annotations

import shlex
import sys
from typing import TextIO

from tpusim.ir import ModuleTrace
from tpusim.timing.config import SimConfig
from tpusim.timing.cost import CostModel

__all__ = ["Debugger"]


class Debugger:
    def __init__(self, module: ModuleTrace, config: SimConfig | None = None):
        self.module = module
        self.config = config or SimConfig()
        self.cost = CostModel(self.config.arch)
        comp = module.entry
        self.ops = comp.ops
        self.comp = comp
        self.pos = 0
        self.t_cycles = 0.0
        self.breakpoints: list[str] = []
        self.counters = {"flops": 0.0, "hbm_bytes": 0.0, "ops": 0}

    # ------------------------------------------------------------------

    def _cost(self, op):
        return self.cost.op_cost(op, self.comp, self.module)

    def _step_one(self, out: TextIO) -> bool:
        if self.pos >= len(self.ops):
            print("(end of schedule)", file=out)
            return False
        op = self.ops[self.pos]
        c = self._cost(op)
        self.t_cycles += c.cycles
        self.counters["flops"] += c.flops
        self.counters["hbm_bytes"] += c.hbm_bytes
        self.counters["ops"] += 1
        print(
            f"[{self.pos:4d}] t={self.t_cycles:12.0f}cy "
            f"{op.opcode:20s} {op.name:32s} "
            f"+{c.cycles:9.0f}cy unit={c.unit.value}",
            file=out,
        )
        self.pos += 1
        return True

    def _hits_breakpoint(self) -> bool:
        if self.pos >= len(self.ops):
            return False
        op = self.ops[self.pos]
        return any(b in op.name or b in op.opcode for b in self.breakpoints)

    # ------------------------------------------------------------------

    def repl(self, in_stream: TextIO | None = None,
             out: TextIO | None = None) -> None:
        in_stream = in_stream or sys.stdin
        out = out or sys.stdout
        print(
            f"tpusim debugger: module {self.module.name!r}, "
            f"{len(self.ops)} entry ops.  's' step, 'c' continue, "
            f"'b <pat>' break, 'q' quit.",
            file=out,
        )
        for raw in in_stream:
            line = raw.strip()
            if not line:
                continue
            try:
                parts = shlex.split(line)
            except ValueError:
                print("?", file=out)
                continue
            cmd, args = parts[0], parts[1:]

            def _int_arg(default: int) -> int:
                try:
                    return int(args[0]) if args else default
                except ValueError:
                    print(f"? not a count: {args[0]!r}", file=out)
                    return 0

            if cmd == "q":
                break
            elif cmd == "s":
                n = _int_arg(1)
                for _ in range(n):
                    if not self._step_one(out):
                        break
            elif cmd == "c":
                stepped = False
                while self.pos < len(self.ops):
                    if stepped and self._hits_breakpoint():
                        op = self.ops[self.pos]
                        print(f"breakpoint: next op is {op.name} "
                              f"({op.opcode})", file=out)
                        break
                    if not self._step_one(out):
                        break
                    stepped = True
                if self.pos >= len(self.ops):
                    print(f"done: {self.t_cycles:.0f} cycles total", file=out)
            elif cmd == "b" and args:
                self.breakpoints.append(args[0])
                print(f"breakpoint #{len(self.breakpoints)} on "
                      f"{args[0]!r}", file=out)
            elif cmd == "l":
                n = _int_arg(5)
                for i in range(self.pos, min(self.pos + n, len(self.ops))):
                    op = self.ops[i]
                    print(f"  [{i:4d}] {op.opcode:20s} {op.name}", file=out)
            elif cmd == "p":
                if self.pos < len(self.ops):
                    op = self.ops[self.pos]
                    c = self._cost(op)
                    print(f"next op : {op.name} ({op.opcode})", file=out)
                    print(f"result  : {op.result}", file=out)
                    print(f"operands: {', '.join(op.operands)}", file=out)
                    print(f"cycles  : {c.cycles:.0f} (compute "
                          f"{c.compute_cycles:.0f} / mem {c.mem_cycles:.0f})",
                          file=out)
                    print(f"bytes   : hbm {c.hbm_bytes:.0f} vmem "
                          f"{c.vmem_bytes:.0f} ici {c.ici_bytes:.0f}",
                          file=out)
                    if op.attrs:
                        keys = ", ".join(sorted(op.attrs)[:8])
                        print(f"attrs   : {keys}", file=out)
                else:
                    print("(end of schedule)", file=out)
            elif cmd == "stats":
                print(f"ops={self.counters['ops']} "
                      f"t={self.t_cycles:.0f}cy "
                      f"flops={self.counters['flops']:.3g} "
                      f"hbm={self.counters['hbm_bytes']:.3g}B", file=out)
            else:
                print("commands: s [n] | c | b <pat> | l [n] | p | stats | q",
                      file=out)
