"""Trace-replay driver.

The rebuild of ``gpu-simulator/main.cc``: parse the command list, maintain
per-stream ordering with cross-stream overlap (the busy-stream gating of
``main.cc:102-115``), model memcpys (``-gpgpu_perf_sim_memcpy`` →
``perf_memcpy_to_gpu``, ``gpu-sim.cc:2116``), launch kernels into the timing
engine, and handle collective commands — which the fork handled as a constant
latency (``main.cc:116-134``) and we hand to the ICI model with real sizes,
groups, and cross-device rendezvous.

Per-device resources: the TensorCore (kernels serialize on it), the host DMA
channel (memcpys), and the ICI port (standalone collectives).  Commands on
one stream execute in order; different streams overlap on different
resources — the same semantics as the reference's stream windowing.
"""

from __future__ import annotations

import time
from collections import Counter, defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from tpusim.ici.detailed import make_collective_model
from tpusim.ici.topology import Topology, torus_for
from tpusim.ir import CommandKind, PodTrace, TraceCommand
from tpusim.obs.hub import NULL_OBS
from tpusim.obs.sampler import CycleWindowSampler
from tpusim.perf.pool import map_ordered, pool_context, resolve_workers
from tpusim.sim.stats import EXIT_SENTINEL, StatsRegistry
from tpusim.timing.config import SimConfig
from tpusim.timing.engine import Engine, EngineResult

__all__ = ["SimDriver", "SimReport", "simulate_trace"]


def _price_segment_worker(item):
    """:mod:`tpusim.perf.pool` worker: price one ``(module, scales)``
    launch class — the unit of the driver's segment-parallel replay.
    Pure: same engine math as the serial path, so the returned counters
    are bit-identical to an in-process run."""
    name, scales = item
    cfg, topo, modules, cache, backend = pool_context()
    if cache is not None:
        from tpusim.perf.cache import CachedEngine

        eng = CachedEngine(
            cfg, topology=topo,
            clock_scale=scales[0], hbm_scale=scales[1],
            result_cache=cache, pricing_backend=backend,
        )
    else:
        eng = Engine(
            cfg, topology=topo,
            clock_scale=scales[0], hbm_scale=scales[1],
            pricing_backend=backend,
        )
    return eng.run(modules[name])


@dataclass
class KernelRecord:
    module: str
    device_id: int
    stream_id: int
    start_cycle: float
    end_cycle: float
    result: EngineResult


@dataclass
class SimReport:
    """Result of replaying one pod trace."""

    config_name: str
    num_devices: int
    device_cycles: dict[int, float] = field(default_factory=dict)
    kernels: list[KernelRecord] = field(default_factory=list)
    totals: EngineResult = field(default_factory=EngineResult)
    memcpy_cycles: float = 0.0
    collective_cmd_cycles: float = 0.0
    wall_seconds: float = 0.0       # host time spent simulating
    stats: StatsRegistry = field(default_factory=StatsRegistry)
    power: object | None = None     # PowerReport when power_enabled
    #: pod-level cycle-window series (tpusim.obs) when instrumented
    samples: object | None = None
    #: the ArchConfig the run used (export paths need clock/power rates)
    arch_config: object | None = None
    dvfs_scale: float = 1.0

    @property
    def cycles(self) -> float:
        return max(self.device_cycles.values(), default=0.0)

    @property
    def sim_rate_kops(self) -> float:
        """Simulated HLO ops per host-second, in K — the
        ``gpgpu_simulation_rate`` analogue (KIPS in BASELINE.md)."""
        if self.wall_seconds <= 0:
            return 0.0
        return self.totals.op_count / self.wall_seconds / 1e3

    def silicon_slowdown(self, arch_clock_hz: float) -> float:
        """Host-seconds per simulated device-second — the
        ``gpgpu_silicon_slowdown`` analogue (``gpgpusim_entrypoint.cc:
        262-268`` prints it every run).  <1 means the simulator runs
        faster than the hardware it models."""
        sim_s = self.cycles / arch_clock_hz if arch_clock_hz > 0 else 0.0
        if sim_s <= 0:
            return 0.0
        return self.wall_seconds / sim_s

    def finalize(self, arch_clock_hz: float) -> None:
        # totals accumulates per-kernel counters; its wall-clock view is the
        # pod's critical path, needed for the derived utilization stats
        self.totals.cycles = self.cycles
        self.totals.seconds = self.cycles / arch_clock_hz
        s = self.stats
        s.set("num_devices", self.num_devices)
        s.set("sim_cycle", self.cycles)
        s.set("sim_elapsed_s", self.cycles / arch_clock_hz)
        s.set("kernel_launches", len(self.kernels))
        s.set("memcpy_cycles", self.memcpy_cycles)
        s.set("collective_cmd_cycles", self.collective_cmd_cycles)
        s.set("simulation_rate_kops", self.sim_rate_kops)
        s.set("silicon_slowdown", self.silicon_slowdown(arch_clock_hz))
        s.update(self.totals.stats_dict(), prefix="tot_")

    def print_report(self, out=None) -> None:
        import sys

        out = out or sys.stdout
        self.stats.print_text(out)
        print(EXIT_SENTINEL, file=out)


class SimDriver:
    """Replays a :class:`PodTrace` under a :class:`SimConfig`."""

    def __init__(
        self,
        config: SimConfig,
        topology: Topology | None = None,
        obs=None,
        faults=None,
        result_cache=None,
        workers: int | None = None,
        pricing_backend: str | None = None,
        cancel=None,
        compile_cache=None,
    ):
        self.config = config
        self.arch = config.arch
        self.topology = topology
        # cooperative cancellation (tpusim.guard.CancelToken | None):
        # checked at command grain in the stream walk below and threaded
        # into every engine (serial walk stride + fastpath blocks).  A
        # tripped token raises OperationCancelled with every cache entry
        # already published — the serve tier maps it to a 504 with the
        # worker's caches warm, the CLI to a clean refusal.
        self.cancel = cancel
        # instrumentation hub (tpusim.obs); the no-op default adds no
        # stats keys and no per-command work
        self.obs = obs if obs is not None else NULL_OBS
        # fault schedule (tpusim.faults.FaultSchedule | path | dict);
        # None = healthy pod, zero added work and zero added stats keys
        self.faults = faults
        # tpusim.perf: engine-result cache (ResultCache | dir path | True
        # for the default disk dir) and worker count for segment-parallel
        # pricing (None = $TPUSIM_WORKERS, else serial).  Both default
        # off: the healthy serial path is unchanged, key-identical.
        if result_cache is not None and result_cache is not False:
            from tpusim.perf.cache import as_result_cache

            self.result_cache = as_result_cache(result_cache, obs=self.obs)
        else:
            self.result_cache = None
        self.workers = workers
        # tpusim.fastpath: pricing-backend request (None = auto-resolve;
        # an EXPLICIT request also stamps the fastpath_* stats block on
        # the report — the faults_* discipline, so default runs stay
        # key-identical)
        self.pricing_backend = pricing_backend
        # tpusim.fastpath.store: the durable compiled-module tier (the
        # --compile-cache flag family: a CompileStore, a dir path, or
        # True for the default store dir).  Activation is process-wide
        # — compiled_for consults it before any compile, price_module
        # publishes after — so the driver's only jobs are coercion and
        # stats stamping.  None leaves whatever is already active
        # (a serve daemon activates at boot, workers inherit) untouched.
        if compile_cache is not None and compile_cache is not False:
            from tpusim.fastpath.store import as_compile_store

            self.compile_store = as_compile_store(compile_cache)
        else:
            self.compile_store = None

    # ------------------------------------------------------------------

    def run(self, pod: PodTrace) -> SimReport:
        t_start = time.perf_counter()
        cfg = self.config
        arch = self.arch
        cancel = self.cancel

        n_devices = max(
            (int(pod.meta.get("num_devices", 0) or 0)),
            max((m.num_devices for m in pod.modules.values()), default=1),
            len(pod.devices) or 1,
        )
        obs = self.obs
        base_topo = self.topology or torus_for(n_devices, arch.name)
        # fault binding: resolve the schedule against this pod's topology
        # once (validates coords/adjacency), then attach the cycle-0 view.
        # Windowed schedules re-resolve the view at each command's issue
        # cycle — kernels pick their chip multipliers and standalone
        # collectives their link view at command grain (a fault window
        # cannot split a single kernel: the whole launch prices under
        # the view active when it issues).
        fault_state = None
        fault_view = None
        if self.faults is not None:
            from tpusim.faults import FaultSchedule, load_fault_schedule

            sched = (
                self.faults if isinstance(self.faults, FaultSchedule)
                else load_fault_schedule(self.faults)
            )
            fault_state = sched.bind(base_topo)
            fault_view = fault_state.view_at(0.0)
        topo = (
            base_topo.with_faults(fault_view) if fault_view is not None
            else base_topo
        )
        coll = make_collective_model(topo, arch.ici, obs=obs)
        if self.result_cache is not None:
            from tpusim.perf.cache import CachedEngine

            def _new_engine(**kw) -> Engine:
                return CachedEngine(
                    cfg, topology=topo, obs=obs,
                    result_cache=self.result_cache,
                    pricing_backend=self.pricing_backend,
                    cancel=self.cancel, **kw,
                )
        else:
            def _new_engine(**kw) -> Engine:
                return Engine(
                    cfg, topology=topo, obs=obs,
                    pricing_backend=self.pricing_backend,
                    cancel=self.cancel, **kw,
                )

        engine = _new_engine()

        # degraded chips run their own engine (straggler clock / HBM
        # throttle multipliers); the healthy class is the default engine
        engines: dict[tuple[float, float], Engine] = {(1.0, 1.0): engine}

        def engine_for(scales: tuple[float, float]) -> Engine:
            e = engines.get(scales)
            if e is None:
                e = engines[scales] = _new_engine(
                    clock_scale=scales[0], hbm_scale=scales[1],
                )
            return e

        # windowed link faults: standalone collectives are priced with
        # the view active at their issue cycle (models cached per view)
        coll_models = {
            (fault_view.signature if fault_view is not None else None): coll
        }

        def coll_for(cycle: float):
            if fault_state is None or not fault_state.windowed:
                return coll
            v = fault_state.view_at(cycle)
            m = coll_models.get(v.signature)
            if m is None:
                m = coll_models[v.signature] = make_collective_model(
                    base_topo.with_faults(v), arch.ici, obs=obs
                )
            return m

        report = SimReport(
            config_name=arch.name, num_devices=n_devices,
            arch_config=arch, dvfs_scale=cfg.dvfs_scale,
        )
        # standalone command events for the pod-level sampler (collective
        # and memcpy commands don't live in any module series)
        obs_sampling = obs.enabled and obs.sample
        cmd_events: list[tuple[str, float, float, float]] = []

        # Kernel timing is per-module (SPMD: all devices run the same
        # program) — cache engine results like the reference caches parsed
        # kernel traces per launch (trace_driven.cc:540-586).  Degraded
        # chips (stragglers / HBM throttles) form their own cache class:
        # the same module re-times under that chip's multipliers.
        module_results: dict[tuple[str, tuple[float, float]], EngineResult] \
            = {}

        def module_result(
            name: str, scales: tuple[float, float] = (1.0, 1.0)
        ) -> EngineResult:
            key = (name, scales)
            if key not in module_results:
                if name not in pod.modules:
                    raise KeyError(
                        f"command references unknown module {name!r}; "
                        f"trace has {sorted(pod.modules)}"
                    )
                with obs.span("engine"):
                    module_results[key] = engine_for(scales).run(
                        pod.modules[name]
                    )
            return module_results[key]

        # Cross-device collective rendezvous: the k-th standalone collective
        # *over a given replica group* must align across that group's
        # members (NCCL call-order matching).  Keyed by (group, index) so
        # disjoint groups never synchronize with each other — a global
        # per-device index would couple unrelated groups' timing exactly in
        # the traces where their collective counts diverge.
        coll_ready: dict[tuple, list[float]] = defaultdict(list)

        device_ids = sorted(pod.devices) or [0]

        def _group_of(cmd: TraceCommand, d: int) -> tuple:
            groups = cmd.collective.replica_groups or []
            mine = next((tuple(g) for g in groups if d in g), None)
            # no groups recorded: all devices participate
            return mine if mine is not None else tuple(device_ids)
        # per-device resource timelines
        core_free = {d: 0.0 for d in device_ids}
        dma_free = {d: 0.0 for d in device_ids}
        ici_free = {d: 0.0 for d in device_ids}
        stream_free: dict[tuple[int, int], float] = defaultdict(float)

        # checkpoint/resume at kernel granularity (per device, like the
        # reference's per-kernel resume that fast-forwards finished work)
        resume_k = max(cfg.resume_kernel, 0)
        checkpoint_k = max(cfg.checkpoint_kernel, 0)

        window = max(cfg.kernel_window, 1)

        # --- tpusim.perf: segment-parallel pricing ----------------------
        # The replay decomposes into per-(module, chip-multiplier) launch
        # classes whose pricing is pure and independent — the segments
        # between stream barriers all draw from this class set.  With
        # workers, the distinct classes price CONCURRENTLY up front; the
        # stream walk below stays serial and consumes the pre-priced
        # results, so every scalar accumulates in the exact serial order
        # (bit-identical reports, pinned by tests/test_perf.py).  The
        # parallel path disengages under obs (samplers are run-scoped),
        # windowed faults (multipliers depend on issue cycle), and
        # checkpoint/resume (classes past the barrier must not price).
        workers = resolve_workers(self.workers)
        pool_segments = 0
        if (
            workers > 1
            and not obs.enabled
            and not (fault_state is not None and fault_state.windowed)
            and not resume_k and not checkpoint_k
        ):
            classes: list[tuple[str, tuple[float, float]]] = []
            seen_classes: set[tuple[str, tuple[float, float]]] = set()
            for dev_id in device_ids:
                dev = pod.devices.get(dev_id)
                if dev is None:
                    continue
                scales = (
                    fault_view.chip_scales(dev_id)
                    if fault_view is not None else (1.0, 1.0)
                )
                for cmd in dev.commands:
                    if (
                        cmd.kind == CommandKind.KERNEL_LAUNCH
                        and cmd.module in pod.modules
                        and (cmd.module, scales) not in seen_classes
                    ):
                        seen_classes.add((cmd.module, scales))
                        classes.append((cmd.module, scales))
            # classes the parent's cache already holds skip the pool
            # entirely (a warm-cache run forks nothing and runs no
            # engine anywhere)
            remaining: list[tuple[str, tuple[float, float]]] = []
            for mkey in classes if len(classes) > 1 else []:
                res = None
                if self.result_cache is not None:
                    ck = self.result_cache.key_for(
                        pod.modules[mkey[0]], cfg, mkey[1], topo
                    )
                    if ck is not None:
                        res = self.result_cache.get(ck)
                if res is not None:
                    module_results[mkey] = res
                else:
                    remaining.append(mkey)
            if len(remaining) > 1:
                if cancel is not None:
                    # last check before forking: pool workers run their
                    # segment to completion (tokens are process-local);
                    # the parent re-checks at every command below
                    cancel.check()
                priced = map_ordered(
                    _price_segment_worker, remaining, workers=workers,
                    context=(cfg, topo, pod.modules, self.result_cache,
                             self.pricing_backend),
                )
                pool_segments = len(remaining)
                for mkey, res in zip(remaining, priced):
                    module_results[mkey] = res
                    if self.result_cache is not None:
                        ck = self.result_cache.key_for(
                            pod.modules[mkey[0]], cfg, mkey[1], topo
                        )
                        if ck is not None:
                            self.result_cache.put(ck, res)

        for dev_id in device_ids:
            dev = pod.devices.get(dev_id)
            if dev is None:
                continue
            dev_scales = (
                fault_view.chip_scales(dev_id)
                if fault_view is not None else (1.0, 1.0)
            )

            def scales_at(cycle: float) -> tuple[float, float]:
                """Chip multipliers for this device at a kernel's issue
                cycle — windowed stragglers/throttles hit only the
                launches their window overlaps."""
                if fault_state is None or not fault_state.windowed:
                    return dev_scales
                return fault_state.view_at(cycle).chip_scales(dev_id)

            coll_counts: Counter = Counter()  # per-group issue index
            kernel_index = 0
            # completion times of this device's kernel launches, in launch
            # order — the stream-window gate (main.cc:74-115): no command
            # may issue while `window` kernels are still in flight, so
            # far-ahead DMA/collective prefetch is bounded
            kernel_ends: list[float] = []
            for cmd in dev.commands:
                # the driver's cancellation grain: a fault window cannot
                # split a command, and neither can a cancel — the whole
                # launch prices or the walk raises before it starts
                if cancel is not None:
                    cancel.check()
                key = (dev_id, cmd.stream_id)
                ready = stream_free[key]
                if len(kernel_ends) >= window:
                    ready = max(ready, kernel_ends[-window])

                # kernel-granularity checkpoint/resume boundary: "after
                # kernel K completes".  The k-th kernel is in the first
                # half iff k <= K; a non-kernel command is in the first
                # half iff fewer than K kernels precede it.  Both runs use
                # the same rule, so first-half + second-half partitions the
                # stream exactly (memcpys/collectives included).
                is_kernel = cmd.kind == CommandKind.KERNEL_LAUNCH
                if is_kernel:
                    kernel_index += 1
                in_first_half = (
                    kernel_index <= resume_k if is_kernel
                    else kernel_index < resume_k
                )
                if resume_k and in_first_half:
                    if cmd.kind == CommandKind.COLLECTIVE and cmd.collective:
                        # keep rendezvous indices aligned
                        coll_counts[_group_of(cmd, dev_id)] += 1
                    continue  # fast-forward already-simulated work
                if checkpoint_k and (
                    kernel_index > checkpoint_k if is_kernel
                    else kernel_index >= checkpoint_k
                ):
                    report.stats.set("checkpoint_stop_kernel", checkpoint_k)
                    break

                if is_kernel:
                    res = module_result(
                        cmd.module,
                        scales_at(max(ready, core_free[dev_id])),
                    )
                    start = max(ready, core_free[dev_id])
                    dur = res.cycles
                    end = start + dur
                    core_free[dev_id] = end
                    stream_free[key] = end
                    kernel_ends.append(end)
                    report.kernels.append(KernelRecord(
                        cmd.module, dev_id, cmd.stream_id, start, end, res
                    ))
                    report.totals.merge_scaled(res, 1.0)

                elif cmd.kind in (CommandKind.MEMCPY_H2D, CommandKind.MEMCPY_D2H):
                    if cfg.perf_sim_memcpy and cmd.nbytes > 0:
                        secs = arch.host_latency + cmd.nbytes / arch.host_bandwidth
                        dur = arch.seconds_to_cycles(secs)
                    else:
                        dur = 0.0
                    start = max(ready, dma_free[dev_id])
                    end = start + dur
                    dma_free[dev_id] = end
                    stream_free[key] = end
                    report.memcpy_cycles += dur
                    if obs_sampling and dur > 0:
                        cmd_events.append(
                            ("dma", start, end, float(cmd.nbytes))
                        )

                elif cmd.kind == CommandKind.COLLECTIVE and cmd.collective:
                    with obs.span("ici"):
                        secs = coll_for(
                            max(ready, ici_free[dev_id])
                        ).seconds(
                            cmd.collective, float(cmd.nbytes)
                        )
                    dur = arch.seconds_to_cycles(secs)
                    start = max(ready, ici_free[dev_id])
                    # rendezvous with the group's k-th collective: all
                    # participants start together at the latest arrival
                    grp = _group_of(cmd, dev_id)
                    k = coll_counts[grp]
                    coll_counts[grp] += 1
                    peers = coll_ready[(grp, k)]
                    if peers:
                        start = max(start, max(peers))
                    coll_ready[(grp, k)].append(start)
                    end = start + dur
                    ici_free[dev_id] = end
                    stream_free[key] = end
                    report.collective_cmd_cycles += dur
                    report.totals.collective_count += 1
                    report.totals.ici_bytes += cmd.nbytes
                    report.totals.collective_cycles += dur
                    if obs_sampling and dur > 0:
                        cmd_events.append(
                            ("ici", start, end, float(cmd.nbytes))
                        )

                else:
                    # comm_init/destroy/group markers: logged no-ops, like
                    # the reference (main.cc:125-133)
                    stream_free[key] = ready

            report.device_cycles[dev_id] = max(
                core_free[dev_id], dma_free[dev_id], ici_free[dev_id],
                max((v for (d, _), v in stream_free.items() if d == dev_id),
                    default=0.0),
            )

        # failure detection: devices that share a replica group must issue
        # the same number of collectives over that group — a ragged count
        # means a device would hang waiting at a rendezvous (the NCCL-hang
        # analog).  Disjoint groups and non-participating devices are fine.
        if coll_ready:
            per_dev_groups: dict[int, Counter] = {}
            for d in device_ids:
                dev = pod.devices.get(d)
                if dev is None:
                    continue
                counts: Counter = Counter()
                for cmd in dev.commands:
                    if cmd.kind != CommandKind.COLLECTIVE or not cmd.collective:
                        continue
                    groups = cmd.collective.replica_groups or []
                    mine = next(
                        (tuple(g) for g in groups if d in g), None
                    )
                    if mine is None:
                        # no groups recorded: all devices participate
                        mine = tuple(device_ids)
                    counts[mine] += 1
                per_dev_groups[d] = counts
            ragged: list[str] = []
            for d, counts in per_dev_groups.items():
                for grp, n in counts.items():
                    for peer in grp:
                        if peer == d or peer not in per_dev_groups:
                            continue
                        if per_dev_groups[peer].get(grp, 0) != n:
                            ragged.append(
                                f"dev{d}:{n}!=dev{peer}:"
                                f"{per_dev_groups[peer].get(grp, 0)}@{grp}"
                            )
            if ragged:
                report.stats.set("collective_rendezvous_mismatch", 1)
                report.stats.set(
                    "collective_counts_per_device", ";".join(sorted(set(ragged)))
                )

        # deadlock/runaway detection (the -gpu_deadlock_detect analogue,
        # gpu-sim.h:443): an analytic replay cannot stall, but a corrupt
        # trace or unresolved loop bound can send the cycle count to
        # absurdity — flag it with the biggest offenders
        if cfg.deadlock_detect and report.cycles > cfg.deadlock_cycles:
            report.stats.set("deadlock_suspected", 1)
            # rank by total contribution (per-run cycles x launch count) —
            # a cheap module launched 10k times can dominate the pod clock
            # while a single-run-expensive module is innocent
            launches = Counter(k.module for k in report.kernels)
            worst = sorted(
                module_results.items(),
                key=lambda kv: -(
                    kv[1].cycles * max(launches.get(kv[0][0], 0), 1)
                ),
            )[:3]
            report.stats.set(
                "deadlock_suspects",
                ";".join(
                    f"{name}:x{max(launches.get(name, 0), 1)}:"
                    f"{r.cycles * max(launches.get(name, 0), 1):.3g}cy"
                    for (name, _), r in worst
                ),
            )

        if obs_sampling:
            # pod assembly: each kernel's module series at its launch
            # offset (devices sum; exports normalize per device), plus
            # the standalone command events no module series covers
            with obs.span("sample"):
                pod_samples = CycleWindowSampler(obs.window_cycles)
                for k in report.kernels:
                    s = k.result.samples
                    if s is not None:
                        pod_samples.add_series(
                            s, offset=k.start_cycle,
                            length=k.end_cycle - k.start_cycle,
                        )
                for unit, s0, s1, nbytes in cmd_events:
                    if unit == "ici":
                        pod_samples.add(unit, s0, s1, ici_bytes=nbytes)
                    else:
                        pod_samples.add(unit, s0, s1, hbm_bytes=nbytes)
                if fault_state is not None and report.cycles > 0:
                    # each active fault contributes its overlap cycles to
                    # the "faults" lane; window_rows divides by the window
                    # to recover the avg active-fault count per window
                    # (the faults_active counter track)
                    for f0, f1 in fault_state.intervals():
                        s0 = max(f0, 0.0)
                        s1 = min(f1, report.cycles)
                        if s1 > s0:
                            pod_samples.add(
                                "faults", s0, s1, op_count=0.0
                            )
                report.samples = pod_samples
                obs.counter_set("samples.windows", pod_samples.num_windows)
                obs.counter_set(
                    "samples.window_cycles", pod_samples.window_cycles
                )

        report.wall_seconds = time.perf_counter() - t_start
        report.finalize(arch.clock_hz)
        # perf-layer accounting rides the report ONLY when the feature is
        # active (the faults_* discipline): serial/uncached runs stay
        # key-identical, and byte-identity comparisons strip these keys.
        if self.result_cache is not None:
            report.stats.update(
                self.result_cache.stats_dict(), prefix="cache_"
            )
            if (
                self.result_cache.quota_bytes is not None
                or self.result_cache.quota_entries is not None
            ):
                # guard_* keys ride the report ONLY when a store quota
                # is actually governing (the faults_* discipline:
                # un-governed runs stay key-identical, goldens pinned)
                report.stats.update(
                    self.result_cache.guard_stats_dict(), prefix="guard_"
                )
        if pool_segments:
            report.stats.update(
                {"workers": workers, "parallel_segments": pool_segments},
                prefix="pool_",
            )
        from tpusim.fastpath.store import get_compile_store

        if self.pricing_backend is not None or \
                get_compile_store() is not None:
            # fastpath accounting rides the report ONLY when a backend
            # was explicitly requested or a durable compile store is
            # active (the faults_*/cache_* discipline: default
            # auto-fastpath runs stay key-identical, goldens
            # unchanged).  The stamped name is what actually priced:
            # under obs instrumentation or op-granularity checkpoint/
            # resume the fastpath disengages and every run took the
            # serial reference walk regardless of the request.
            from tpusim.fastpath import resolve_backend
            from tpusim.perf.cache import compiled_cache_stats

            resolved = resolve_backend(self.pricing_backend)
            if obs.enabled or cfg.resume_op or cfg.checkpoint_op:
                resolved = "serial"
            report.stats.set("fastpath_backend", resolved)
            report.stats.update(compiled_cache_stats(), prefix="fastpath_")
        if fault_state is not None:
            # faults_* keys ride the report ONLY when a schedule is
            # active — the healthy path stays key-identical to PR 1.
            # Counts describe the whole schedule (windowed faults
            # included), not just the cycle-0 snapshot.
            report.stats.update(fault_state.full_view().stats_dict())
            worst_occ = getattr(obs, "counters", {}).get(
                "ici.detailed.worst_link_occupancy"
            )
            if worst_occ is not None:
                report.stats.set("faults_worst_link_occupancy", worst_occ)
        from tpusim.dcn import slice_topology_for

        slice_topo = slice_topology_for(base_topo.num_chips, cfg.arch.ici)
        if slice_topo is not None and slice_topo.num_slices > 1:
            # dcn_* keys ride the report ONLY when a DCN fabric is
            # configured AND this pod actually spans slices (the
            # faults_* discipline: single-slice and fabric-less runs
            # stay key-identical, goldens pinned)
            report.stats.update({
                "dcn_slices": slice_topo.num_slices,
                "dcn_chips_per_slice": slice_topo.chips_per_slice,
                "dcn_nics_per_slice": slice_topo.nics_per_slice,
                "dcn_slice_bandwidth": slice_topo.slice_bandwidth(),
            })
        if cfg.power_enabled:
            from tpusim.power.model import PowerModel

            with obs.span("power"):
                preport = PowerModel(
                    arch.name, dvfs_scale=cfg.dvfs_scale
                ).report(report.totals)
            report.stats.update(preport.stats_dict(), prefix="")
            report.power = preport
        if obs.enabled:
            # the obs keys ride the same greppable/JSON report; the
            # disabled path adds none (pinned by tests/test_obs.py)
            report.stats.update(obs.stats_dict(), prefix="obs_")
        return report


def simulate_trace(
    trace_path: str | Path,
    config: SimConfig | None = None,
    arch: str | None = None,
    overlays: list[Any] | None = None,
    tuned: bool = True,
    obs=None,
    faults=None,
    topology: Topology | None = None,
    lenient: bool = False,
    validate: str | bool | None = None,
    result_cache=None,
    workers: int | None = None,
    pricing_backend: str | None = None,
    cancel=None,
    max_wall_s: float | None = None,
    compile_cache=None,
) -> SimReport:
    """One-call CLI-style entry: load a trace dir, pick a config, replay.

    The ``accel-sim.out -trace ... -config ...`` equivalent
    (``main.cc:55-206``).  ``tuned=False`` skips the committed tuner
    overlay — golden regression sims pin it off so their stats don't
    shift when a live run refreshes the fit.  ``obs`` is an
    :class:`tpusim.obs.hub.Instrumentation` for spans + cycle-window
    sampling (None = the no-op hub).  ``faults`` is a fault schedule
    (``tpusim.faults.FaultSchedule`` / path / dict — the ``--faults``
    flag); ``lenient`` tolerates malformed HLO lines during parse (the
    ``--lenient-parse`` flag).  ``validate`` opts into the static
    pre-flight (the ``--validate[=strict]`` flag): the trace, composed
    config, and fault schedule run through ``tpusim.analysis`` first,
    and error-level diagnostics (plus warnings under ``"strict"``)
    raise :class:`tpusim.analysis.ValidationError` instead of pricing a
    replay that would be silently wrong.  ``result_cache`` (the
    ``--result-cache[=DIR]`` flag: a :class:`tpusim.perf.ResultCache`,
    a directory path, or True for the default dir) memoizes engine
    results across runs; ``workers`` (``--workers`` /
    ``$TPUSIM_WORKERS``) fans module pricing over a process pool — both
    bit-identical to the serial path.  ``pricing_backend`` (the
    ``--pricing-backend`` flag / ``$TPUSIM_PRICING_BACKEND``) pins the
    tpusim.fastpath engine backend (auto/serial/vectorized/native; all
    byte-identical) and stamps the ``fastpath_*`` stats block.
    ``cancel`` (a :class:`tpusim.guard.CancelToken`) / ``max_wall_s``
    (the ``--max-wall-s`` flag) make the replay cooperatively
    cancellable: a tripped token raises
    :class:`tpusim.guard.OperationCancelled` at the next command/op
    boundary instead of pricing to completion.  ``compile_cache`` (the
    ``--compile-cache[=DIR]`` flag) mounts the durable compiled-module
    tier before the trace loads, so the parse defers and a warm store
    prices with zero IR construction."""
    from tpusim.timing.config import load_config
    from tpusim.trace.format import load_trace

    obs = obs if obs is not None else NULL_OBS
    if compile_cache is not None and compile_cache is not False:
        # activated BEFORE the parse span: load_trace defers IR
        # construction exactly when the compiled tier may serve it
        # (the coerced instance rides into the driver so it isn't
        # re-coerced — counters are cumulative per instance)
        from tpusim.fastpath.store import as_compile_store

        compile_cache = as_compile_store(compile_cache)
    if max_wall_s is not None and cancel is None:
        from tpusim.guard.cancel import CancelToken

        cancel = CancelToken.after(max_wall_s)
    if validate:
        from tpusim.analysis import (
            Severity, ValidationError, analyze_trace_dir,
        )

        strict = validate == "strict"
        with obs.span("validate"):
            # the explicitly passed config/topology are what replays,
            # so they are what gets analyzed; `lenient` decides whether
            # salvage damage is fatal (strict parse) or a warning
            diags = analyze_trace_dir(
                trace_path, arch=arch, overlays=overlays,
                faults=faults, tuned=tuned, config=config,
                topology=topology, lenient=lenient,
            )
        if diags.has_errors or (
            strict and diags.count(Severity.WARNING) > 0
        ):
            raise ValidationError(diags, strict=strict)
    with obs.span("parse"):
        pod = load_trace(trace_path, lenient=lenient)
    if arch is None and config is None:
        # default the arch to the one the trace was captured on, via the
        # named-preset route so the committed tuner overlay applies
        kind = str(pod.meta.get("device_kind", ""))
        if kind:
            from tpusim.timing.arch import detect_arch

            arch = detect_arch(kind).name
    with obs.span("config"):
        cfg = load_config(config, arch=arch, overlays=overlays, tuned=tuned)
    with obs.span("simulate"):
        return SimDriver(
            cfg, topology=topology, obs=obs, faults=faults,
            result_cache=result_cache, workers=workers,
            pricing_backend=pricing_backend, cancel=cancel,
            compile_cache=compile_cache,
        ).run(pod)
