"""Interval-sampled statistics — the per-window time series the reference
emits every ``gpu_stat_sample_freq`` cycles for AerialVision
(``gpu-sim.cc:2042+`` sampling, ``src/gpgpu-sim/visualizer.cc`` gzip'd
``gpgpusim_visualizer__*.log.gz`` writers, viewer ``aerialvision/``).

tpusim derives the series from the engine's recorded timeline: each
``stat_sample_cycles`` window gets per-unit busy cycles, op counts, and
utilization.  Output is a gzip'd JSONL log (one sample per line — the
visualizer-log analogue) and a terminal time-lapse heat view
(``python -m tpusim aerial``) in place of the bespoke matplotlib GUI.
"""

from __future__ import annotations

import gzip
import json
import math
from dataclasses import dataclass, field
from pathlib import Path

from tpusim.timing.engine import EngineResult

__all__ = [
    "IntervalSample",
    "sample_intervals",
    "write_interval_log",
    "read_interval_log",
    "render_text_lanes",
    "render_scalar_lane",
]


@dataclass
class IntervalSample:
    """One ``stat_sample_cycles`` window."""

    t0: float
    t1: float
    unit_busy: dict[str, float] = field(default_factory=dict)
    op_count: int = 0

    def utilization(self, unit: str) -> float:
        span = self.t1 - self.t0
        return self.unit_busy.get(unit, 0.0) / span if span > 0 else 0.0

    def to_dict(self) -> dict:
        return {
            "t0": self.t0,
            "t1": self.t1,
            "unit_busy": self.unit_busy,
            "op_count": self.op_count,
        }


def sample_intervals(
    result: EngineResult,
    sample_cycles: float,
    offset: float = 0.0,
) -> list[IntervalSample]:
    """Bucket a recorded timeline into fixed windows.

    An event spanning several windows contributes proportionally to each
    (the busy-cycle accounting the reference does at sample boundaries).
    ``offset`` shifts event times (e.g. a kernel's start cycle within a
    multi-kernel replay).
    """
    if sample_cycles <= 0:
        raise ValueError("sample_cycles must be positive")
    if not result.timeline:
        return []
    end = max(ev.end_cycle for ev in result.timeline) + offset
    n_windows = max(int(math.ceil(end / sample_cycles)), 1)
    samples = [
        IntervalSample(i * sample_cycles, (i + 1) * sample_cycles)
        for i in range(n_windows)
    ]
    for ev in result.timeline:
        s, e = ev.start_cycle + offset, ev.end_cycle + offset
        if e <= s:
            # zero-duration events still count as ops in their window
            idx = min(int(s // sample_cycles), n_windows - 1)
            samples[idx].op_count += 1
            continue
        first = int(s // sample_cycles)
        last = min(int((e - 1e-12) // sample_cycles), n_windows - 1)
        samples[first].op_count += 1
        for w in range(first, last + 1):
            w0, w1 = samples[w].t0, samples[w].t1
            overlap = min(e, w1) - max(s, w0)
            if overlap > 0:
                ub = samples[w].unit_busy
                ub[ev.unit] = ub.get(ev.unit, 0.0) + overlap
    return samples


def write_interval_log(
    samples: list[IntervalSample], path: str | Path, meta: dict | None = None
) -> None:
    """Gzip'd JSONL: header line then one sample per line (the
    ``gpgpusim_visualizer__*.log.gz`` analogue)."""
    with gzip.open(path, "wt") as f:
        f.write(json.dumps({"tpusim_interval_log": 1, **(meta or {})}) + "\n")
        for s in samples:
            f.write(json.dumps(s.to_dict()) + "\n")


def read_interval_log(path: str | Path) -> tuple[dict, list[IntervalSample]]:
    with gzip.open(path, "rt") as f:
        header = json.loads(f.readline())
        if "tpusim_interval_log" not in header:
            raise ValueError(f"{path} is not a tpusim interval log")
        samples = []
        for line in f:
            d = json.loads(line)
            samples.append(IntervalSample(
                d["t0"], d["t1"], d.get("unit_busy", {}),
                d.get("op_count", 0),
            ))
    return header, samples


_BLOCKS = " ▁▂▃▄▅▆▇█"


def render_text_lanes(
    samples: list[IntervalSample],
    units: list[str] | None = None,
    width: int = 72,
) -> str:
    """Terminal time-lapse: one lane per unit, one char per (resampled)
    window, glyph height = utilization."""
    if not samples:
        return "(no samples)\n"
    if units is None:
        seen: dict[str, float] = {}
        for s in samples:
            for u, b in s.unit_busy.items():
                seen[u] = seen.get(u, 0.0) + b
        units = [u for u, _ in sorted(seen.items(), key=lambda kv: -kv[1])]
    # resample to at most `width` columns
    cols = min(len(samples), width)
    per = len(samples) / cols
    lines = []
    total_span = samples[-1].t1 - samples[0].t0
    lines.append(
        f"interval log: {len(samples)} windows x "
        f"{samples[0].t1 - samples[0].t0:.0f} cycles "
        f"(total {total_span:.3g} cycles)"
    )
    for u in units:
        chars = []
        for c in range(cols):
            lo, hi = int(c * per), max(int((c + 1) * per), int(c * per) + 1)
            chunk = samples[lo:hi]
            util = sum(s.utilization(u) for s in chunk) / len(chunk)
            chars.append(_BLOCKS[min(int(util * (len(_BLOCKS) - 1) + 0.5),
                                     len(_BLOCKS) - 1)])
        lines.append(f"{u:>7s} |{''.join(chars)}|")
    return "\n".join(lines) + "\n"


def render_scalar_lane(
    values: list[float], label: str, width: int = 72,
    suffix: str = "",
) -> str:
    """One sparkline lane for an arbitrary per-window scalar series (e.g.
    watts), using the same glyph ramp and quantization as the unit lanes."""
    if not values:
        return f"{label:>7s} |{'':{width}}|{suffix}\n"
    peak = max(values) or 1.0
    cols = min(len(values), width)
    per = len(values) / cols
    chars = []
    for c in range(cols):
        lo, hi = int(c * per), max(int((c + 1) * per), int(c * per) + 1)
        chunk = values[lo:hi]
        v = sum(chunk) / len(chunk) / peak
        chars.append(_BLOCKS[min(int(v * (len(_BLOCKS) - 1) + 0.5),
                                 len(_BLOCKS) - 1)])
    return f"{label:>7s} |{''.join(chars)}|{suffix}\n"
