"""Stats registry + report formatting.

The reference prints ~300 ``name = value`` lines per kernel
(``gpgpu_sim::print_stats``, ``gpu-sim.h:550-579``) and downstream tooling
scrapes them with YAML-configured regexes
(``util/job_launching/stats/example_stats.yml``), keyed on the success
sentinel ``GPGPU-Sim: *** exit detected ***``
(``util/job_launching/get_stats.py:224-246``).

We keep both contracts — stable greppable text lines *and* a structured JSON
dump (SURVEY.md §7: "structured stats (JSON) plus stable text lines") — and
keep a single success sentinel so monitoring works the same way.
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field
from typing import Any, TextIO

__all__ = ["StatsRegistry", "EXIT_SENTINEL", "format_stat_lines"]

#: the run-succeeded marker; the scraper requires it, like the reference's
#: "GPGPU-Sim: *** exit detected ***".
EXIT_SENTINEL = "TPUSIM: *** exit detected ***"

STAT_PREFIX = "tpusim_"


@dataclass
class StatsRegistry:
    """Flat name→value counter store with grouped formatting."""

    values: dict[str, Any] = field(default_factory=dict)

    def set(self, name: str, value: Any) -> None:
        self.values[name] = value

    def add(self, name: str, delta: float) -> None:
        self.values[name] = self.values.get(name, 0) + delta

    def get(self, name: str, default: Any = None) -> Any:
        return self.values.get(name, default)

    def update(self, other: dict[str, Any], prefix: str = "") -> None:
        for k, v in other.items():
            self.values[prefix + k] = v

    # -- output ------------------------------------------------------------

    def text_lines(self) -> list[str]:
        lines = []
        for name in sorted(self.values):
            v = self.values[name]
            if isinstance(v, float):
                v = f"{v:.6g}"
            lines.append(f"{STAT_PREFIX}{name} = {v}")
        return lines

    def print_text(self, out: TextIO = sys.stdout) -> None:
        for line in self.text_lines():
            print(line, file=out)

    def to_json(self) -> str:
        return json.dumps(self.values, indent=2, sort_keys=True, default=str)

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")


def format_stat_lines(stats: dict[str, Any]) -> str:
    reg = StatsRegistry(dict(stats))
    return "\n".join(reg.text_lines())
