"""Timeline export — the AerialVision-slot visualizer
(``src/gpgpu-sim/visualizer.cc`` + ``aerialvision/`` in the reference).

Instead of gzip'd custom logs + a bespoke GUI, the engine's per-op timeline
is exported as Chrome trace-event JSON — loadable in ``chrome://tracing`` /
Perfetto, which is the de-facto viewer for accelerator timelines.  Rows
(tids) are the modeled units (MXU/VPU/DMA/ICI/...), so compute/collective
overlap is visible directly.
"""

from __future__ import annotations

import json
from pathlib import Path

from tpusim.timing.config import ArchConfig
from tpusim.timing.engine import EngineResult

__all__ = ["timeline_to_chrome_trace", "write_chrome_trace"]

_UNIT_ROWS = {
    "mxu": 1, "vpu": 2, "xpose": 3, "scalar": 4, "dma": 5, "ici": 6,
    "none": 7,
}


def timeline_to_chrome_trace(
    result: EngineResult, arch: ArchConfig, process_name: str = "tpusim",
    extra_events: list[dict] | None = None,
) -> dict:
    """Convert a recorded timeline to the Chrome trace-event format.

    ``extra_events`` lets callers merge additional trace events into the
    same process — the observability layer appends its Perfetto counter
    tracks (``tpusim.obs.export.counter_track_events``) here so sampled
    utilization/bandwidth/power series render above the op rows."""
    events = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": process_name}},
    ]
    for unit, tid in _UNIT_ROWS.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
            "args": {"name": unit},
        })
    us_per_cycle = 1e6 / arch.clock_hz
    for ev in result.timeline:
        dur = (ev.end_cycle - ev.start_cycle) * us_per_cycle
        events.append({
            "name": f"{ev.opcode}:{ev.name}",
            "ph": "X",
            "pid": 0,
            "tid": _UNIT_ROWS.get(ev.unit, 7),
            "ts": ev.start_cycle * us_per_cycle,
            "dur": max(dur, 0.001),
            "args": {"op": ev.name, "opcode": ev.opcode, "unit": ev.unit},
        })
    if extra_events:
        events.extend(extra_events)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(
    result: EngineResult, arch: ArchConfig, path: str | Path,
    process_name: str = "tpusim",
    extra_events: list[dict] | None = None,
) -> None:
    with open(path, "w") as f:
        json.dump(
            timeline_to_chrome_trace(
                result, arch, process_name, extra_events=extra_events
            ),
            f,
        )
