"""Cycle-level TPU TensorCore timing model.

The rebuild of the reference's performance core
(``gpu-simulator/gpgpu-sim/src/gpgpu-sim/``: ``gpu-sim.cc`` clock domains,
``shader.cc`` SM pipeline, ``dram.cc``/``gpu-cache.cc`` memory system) at the
granularity that matches how a TPU actually executes: one scheduled HLO op at
a time on a TensorCore, with async DMA and ICI transfers overlapping compute.
"""

from tpusim.timing.config import ArchConfig, SimConfig, load_config, parse_flag_file
from tpusim.timing.arch import ARCH_PRESETS, arch_preset
from tpusim.timing.cost import CostModel, OpCost
from tpusim.timing.engine import Engine, EngineResult

__all__ = [
    "ArchConfig",
    "SimConfig",
    "load_config",
    "parse_flag_file",
    "ARCH_PRESETS",
    "arch_preset",
    "CostModel",
    "OpCost",
    "Engine",
    "EngineResult",
]
