"""TPU generation presets.

The analogue of the reference's tested machine configs
(``gpu-simulator/gpgpu-sim/configs/tested-cfgs/SM7_QV100/gpgpusim.config``,
``SM7_TITANV``, ``SM75_RTX2060`` ...): one vetted parameter set per chip.

Numbers come from public sources (Google Cloud TPU docs, the "How to Scale
Your Model" scaling book's hardware tables) and are chosen so the derived
peak matches the published spec:

=====  ======  =====  ==========  ==========  =========  ==========
gen    clock   MXUs   MXU size    bf16 peak   HBM BW     ICI/link
=====  ======  =====  ==========  ==========  =========  ==========
v4     1.05    8      128x128     275 TF/s    1228 GB/s  3D, 45 GB/s
v5e    1.67    4      128x128     219 TF/s    819 GB/s   2D, 45 GB/s
v5p    1.75    8      128x128     459 TF/s    2765 GB/s  3D, 90 GB/s
v6e    1.75    4      256x256     918 TF/s    1640 GB/s  2D, 90 GB/s
=====  ======  =====  ==========  ==========  =========  ==========

(derived peak = 2 * mxus * rows * cols * clock; e.g. v5p:
2*8*128*128*1.75e9 = 458.8e12 ✓)

The v5e clock is calibrated against silicon, not the announced spec: a
compute-bound bf16 matmul chain sustains 219 TFLOP/s on a real v5e chip
(measured via the correlation harness), which is exactly 4 MXUs at
1.67 GHz — the commonly announced 197 TF/s corresponds to 1.5 GHz and
underestimates the hardware.

The tuner harness (:mod:`tpusim.harness.tuner`) refines these against a live
chip, mirroring ``util/tuner/tuner.py``.
"""

from __future__ import annotations

from tpusim.timing.config import ArchConfig, IciConfig

__all__ = ["ARCH_PRESETS", "arch_preset", "detect_arch", "match_device_kind"]


def _v4() -> ArchConfig:
    return ArchConfig(
        name="v4",
        clock_ghz=1.05,
        mxu_count=8, mxu_rows=128, mxu_cols=128,
        hbm_bandwidth=1228e9, hbm_gib=32.0,
        vmem_bytes=128 * 1024 * 1024,
        ici=IciConfig(topology="torus3d", link_bandwidth=45e9),
    )


def _v5e() -> ArchConfig:
    return ArchConfig(
        name="v5e",
        clock_ghz=1.67,
        mxu_count=4, mxu_rows=128, mxu_cols=128,
        hbm_bandwidth=819e9, hbm_gib=16.0,
        vmem_bytes=128 * 1024 * 1024,
        ici=IciConfig(topology="torus2d", link_bandwidth=45e9),
    )


def _v5p() -> ArchConfig:
    return ArchConfig(
        name="v5p",
        clock_ghz=1.75,
        mxu_count=8, mxu_rows=128, mxu_cols=128,
        hbm_bandwidth=2765e9, hbm_gib=95.7,
        vmem_bytes=128 * 1024 * 1024,
        ici=IciConfig(topology="torus3d", link_bandwidth=90e9),
    )


def _v6e() -> ArchConfig:
    return ArchConfig(
        name="v6e",
        clock_ghz=1.75,
        mxu_count=4, mxu_rows=256, mxu_cols=256,
        hbm_bandwidth=1640e9, hbm_gib=32.0,
        vmem_bytes=128 * 1024 * 1024,
        ici=IciConfig(topology="torus2d", link_bandwidth=90e9),
    )


ARCH_PRESETS: dict[str, "ArchConfig"] = {
    "v4": _v4(),
    "v5e": _v5e(),
    "v5p": _v5p(),
    "v6e": _v6e(),
}

#: map from jax ``device_kind`` strings to preset names.
_DEVICE_KIND_MAP = {
    "tpu v4": "v4",
    "tpu v5 lite": "v5e",
    "tpu v5e": "v5e",
    "tpu v5": "v5p",
    "tpu v5p": "v5p",
    "tpu v6 lite": "v6e",
    "tpu v6e": "v6e",
}


def arch_preset(name: str) -> ArchConfig:
    key = name.lower()
    if key not in ARCH_PRESETS:
        raise KeyError(
            f"unknown arch preset {name!r}; available: {sorted(ARCH_PRESETS)}"
        )
    return ARCH_PRESETS[key]


def match_device_kind(device_kind: str) -> str | None:
    """Preset name a ``device_kind`` CONFIDENTLY maps to, or None when
    it is unrecognized — callers that must not guess (the static
    analyzer\'s trace/config agreement check) key on the None."""
    kind = device_kind.lower().strip()
    if kind in _DEVICE_KIND_MAP:
        return _DEVICE_KIND_MAP[kind]
    for pat, preset in sorted(
        _DEVICE_KIND_MAP.items(), key=lambda kv: -len(kv[0])
    ):
        if kind.startswith(pat):
            return preset
    return None


def detect_arch(device_kind: str) -> ArchConfig:
    """Best-effort map of a jax ``device.device_kind`` to a preset
    (``'TPU v5 lite'`` → v5e).  Falls back to v5e."""
    return arch_preset(match_device_kind(device_kind) or "v5e")
