"""Typed configuration system.

The rebuild of the reference's option registry (``src/option_parser.{h,cc}``,
used ~300× via ``option_parser_register``) and its config-composition scheme
(base ``gpgpusim.config`` + per-benchmark overlays + ``extra_params``
concatenation, ``util/job_launching/run_simulations.py:303-328``).

Design changes, per SURVEY.md §7: configs are **typed dataclasses** instead of
a stringly-typed flag soup, but the composability is preserved — a named arch
preset, overlaid with dicts, JSON files, or reference-style ``-flag value``
flag files (so run dirs can still concatenate overlays the way
``append_gpgpusim_config`` does).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field, fields
from pathlib import Path
from typing import Any

__all__ = [
    "ArchConfig",
    "IciConfig",
    "SimConfig",
    "load_config",
    "parse_flag_file",
    "overlay",
    "tuned_overlay_path",
    "CONFIG_FIELD_RULES",
]


@dataclass(frozen=True)
class IciConfig:
    """Inter-chip interconnect parameters (the ``icnt`` config equivalent —
    reference: ``-network_mode`` + intersim config, ``icnt_wrapper.h:36-64``).
    """

    topology: str = "torus3d"          # torus3d | torus2d | mesh2d | ring
    # per-link, per-direction bandwidth in bytes/second
    link_bandwidth: float = 90e9
    # serialization latency per hop (seconds): SerDes + router
    hop_latency: float = 1e-6
    # software/launch latency per collective (seconds)
    launch_latency: float = 2e-6
    # links per chip per torus axis direction (1 = single link each way)
    links_per_axis: int = 1
    # fraction of peak link bandwidth achievable (protocol efficiency)
    efficiency: float = 0.85
    # DCN (multi-slice) parameters, used when a group spans slices
    dcn_bandwidth: float = 25e9
    dcn_latency: float = 10e-6
    chips_per_slice: int = 0            # 0 = single slice
    # modeled DCN fabric (tpusim.dcn): per-slice NIC count gates the
    # whole fabric — 0 leaves the flat dcn_bandwidth/dcn_latency scalar
    # model in charge (byte-identical to the pre-fabric pricing)
    dcn_nics_per_slice: int = 0
    # per-NIC-hop bandwidth (bytes/s) and latency (s); 0 falls back to
    # dcn_bandwidth / dcn_latency so a fabric can be enabled by NIC
    # count alone
    dcn_hop_bandwidth: float = 0.0
    dcn_hop_latency: float = 0.0
    # spine oversubscription factor (>= 1 divides usable bandwidth)
    dcn_oversubscription: float = 1.0
    # network implementation (the -network_mode equivalent):
    # "analytic" = closed-form schedule math (collectives.py);
    # "detailed" = per-packet link contention sim (detailed.py / ici_net.cpp)
    network_mode: str = "analytic"
    # packet size the detailed network splits transfers into
    packet_bytes: float = 16384.0


@dataclass(frozen=True)
class ArchConfig:
    """One TPU generation's TensorCore + memory + ICI parameters.

    The analogue of a ``gpgpusim.config`` machine section
    (``configs/tested-cfgs/SM7_QV100/gpgpusim.config:64-166``: SM count,
    clocks, mem controllers) plus the ``trace.config`` latency tables.
    """

    name: str = "v5p"
    # --- clocks -----------------------------------------------------------
    clock_ghz: float = 1.75

    # --- MXU (systolic array) --------------------------------------------
    mxu_count: int = 8
    mxu_rows: int = 128
    mxu_cols: int = 128
    # pipeline fill/drain latency (cycles), paid once per matmul op
    mxu_fill_cycles: int = 128
    # minimum cycles per systolic pass: the next pass's weight tile loads
    # while the current one streams (double-buffered), so a pass can't
    # retire faster than the weight load — the floor small-m matmuls hit
    # (fit against the lstm_layer silicon fixture, round 4)
    mxu_weight_stall_cycles: int = 64
    # sustained fraction of the systolic-pass rate on large matmuls
    # (pipeline bubbles, operand skew): v5e silicon sustains 190.4 TF/s
    # of a 219 TF/s modeled peak on a 4096^3 bf16 matmul (0.87)
    mxu_efficiency: float = 1.0
    # dtype multiplier: relative MAC throughput vs bf16
    dtype_mult: dict[str, float] = field(
        default_factory=lambda: {
            "bf16": 1.0, "f16": 1.0,
            "f32": 0.25,           # fp32 via multi-pass on the MXU
            "f64": 0.05,
            "s8": 2.0, "u8": 2.0, "s4": 4.0, "u4": 4.0,
            "f8e4m3": 2.0, "f8e5m2": 2.0, "f8e4m3fn": 2.0,
            "s32": 0.25, "u32": 0.25,
        }
    )

    # --- VPU --------------------------------------------------------------
    vpu_sublanes: int = 8
    vpu_lanes: int = 128
    vpu_alus: int = 4                  # parallel ALU ops per lane per cycle
    # transcendental ops (exp/log/tanh/...) per cycle across the VPU
    # (a rate, not a count — the tuner/refiner fit fractional values)
    vpu_transcendental_per_cycle: float = 512.0
    # reductions accumulate below elementwise rate; the per-element cost
    # scales with dtype width (the VPU accumulates packed words), so this
    # is normalized to f32: a v5e f32 2D-sum measured 9.2x elementwise
    # rate, and the same formula lands the bf16 row-sum at 4.6x
    vpu_reduce_slowdown: float = 9.2
    # extra cycles per OUTPUT element when the reduced dims include the
    # minor (lane) dimension — the lane-shuffle tail of a [.,128]->[.]
    # GEMV-style reduce (decode_step fixture)
    vpu_lane_cross_cycles: float = 0.7
    # spatial convolutions pay an im2col/emitter overhead the pure
    # systolic-pass model can't see (conv2d fixture: 3x3 conv sustains
    # 0.83 of the modeled pass-streaming rate)
    mxu_conv_tap_efficiency: float = 0.83

    # --- scalar / control -------------------------------------------------
    scalar_op_cycles: int = 1
    # fixed per-HLO-op dispatch overhead in cycles (sequencer + DMA setup)
    op_overhead_cycles: int = 35

    # --- memory -----------------------------------------------------------
    # per-noncontiguous-row cost of a scattered gather/scatter (DMA
    # descriptor issue + row-granular HBM access); the embedding fixture
    # read -50% without it (VERDICT r3 #7).  Charged per gathered row, so
    # a random 2KB-row embedding lookup runs well below stream bandwidth
    gather_row_overhead_cycles: int = 16
    # async DMA start latency (descriptor setup + first-byte), seconds.
    # Overlaps across transfers (TPUs have many DMA engines) but delays
    # each transfer's completion: an 8KB per-iteration copy-start measured
    # 1.57us on v5e silicon (lstm fixture) — pure latency, not bandwidth
    dma_issue_latency: float = 1.4e-6
    # a layout-changing copy is a physical relayout (tile shuffle through
    # the vector unit), streaming well below the plain-copy rate: the
    # conv2d fixture's HBM->vmem transposing copy ran at 0.42x the
    # same-layout stream bandwidth
    relayout_efficiency: float = 0.45
    # relayouts that keep the minor (lane) dimension dense in 128-lane
    # tiles move contiguous 256B+ runs — tile reordering, not element
    # shuffling — at near-stream rate (decode fixture: a 33.5MB
    # {4,3,2,1,0}->{4,1,3,2,0} HBM->vmem copy, minor dim 128 on both
    # sides, achieved 452GB/s = 0.66x pin while conv2d's 64-lane
    # transposing copy ran at 0.40x)
    relayout_lane_efficiency: float = 0.66
    # minimum device cycles for a standalone sub-tile kernel: a bare
    # slice/DUS of less than a tile, or a scalar-output reduce, still
    # pays sequencer dispatch + sublane addressing + scalar writeback
    # (v5e silicon: [1,1] slices 229-567ns, a scalar reduce-fusion
    # 329ns, a one-row DUS 594ns — while the model's roofline floor is
    # ~5ns; XLA's own cost model floors the same kernels at ~1830
    # estimated_cycles)
    small_kernel_floor_cycles: int = 700
    # vmem->vmem copies stream through load/store ports, not at the full
    # banked vmem bandwidth the roofline uses for fused operand reads
    # (conv2d %copy.11: 6.4MB same-layout vmem copy at 2.4TB/s vs the
    # 8.2TB/s operand-streaming rate)
    vmem_copy_efficiency: float = 0.3
    # pure data-movement fusions (dynamic-slice/DUS chains, e.g. KV-cache
    # reads) run at DMA slice rate rather than operand-streaming rate
    # (decode fixture: 16.8MB vmem slice at 4.1TB/s aggregate)
    vmem_slice_efficiency: float = 0.5
    hbm_bandwidth: float = 2765e9      # bytes/sec, pin peak
    # achieved fraction of peak for streaming access (refresh, bank
    # conflicts, DMA gaps); calibrated on v5e silicon via bench.py
    hbm_efficiency: float = 0.72
    hbm_latency: float = 700e-9        # seconds, first-byte
    hbm_gib: float = 95.7
    vmem_bytes: int = 128 * 1024 * 1024
    vmem_bandwidth_mult: float = 10.0  # vmem bw as multiple of HBM bw
    # host <-> HBM (PCIe/DMA) for infeed/outfeed & memcpy modeling
    host_bandwidth: float = 32e9
    host_latency: float = 5e-6

    # --- ICI --------------------------------------------------------------
    ici: IciConfig = field(default_factory=IciConfig)

    # --- derived ----------------------------------------------------------
    @property
    def clock_hz(self) -> float:
        return self.clock_ghz * 1e9

    @property
    def mxu_flops_per_cycle(self) -> float:
        """Peak bf16 FLOPs per cycle across all MXUs (2 flops per MAC)."""
        return 2.0 * self.mxu_count * self.mxu_rows * self.mxu_cols

    @property
    def peak_bf16_flops(self) -> float:
        return self.mxu_flops_per_cycle * self.clock_hz

    @property
    def vpu_flops_per_cycle(self) -> float:
        return float(self.vpu_sublanes * self.vpu_lanes * self.vpu_alus)

    @property
    def hbm_bytes_per_cycle(self) -> float:
        return self.hbm_bandwidth * self.hbm_efficiency / self.clock_hz

    @property
    def vmem_bytes_per_cycle(self) -> float:
        return self.vmem_bandwidth_mult * self.hbm_bandwidth / self.clock_hz

    def seconds_to_cycles(self, s: float) -> float:
        return s * self.clock_hz

    def cycles_to_seconds(self, c: float) -> float:
        return c / self.clock_hz

    def mxu_dtype_mult(self, dtype: str) -> float:
        return self.dtype_mult.get(dtype, 0.25)


@dataclass(frozen=True)
class SimConfig:
    """Simulation-run knobs (the driver/behavioral flags of ``gpu-sim.h``:
    stream windowing ``main.cc:74-115``, deadlock detect, stat sampling)."""

    arch: ArchConfig = field(default_factory=ArchConfig)
    # max kernels in flight across streams (reference: window of concurrent
    # kernels, main.cc:74)
    kernel_window: int = 8
    # model memcpy time (reference: -gpgpu_perf_sim_memcpy)
    perf_sim_memcpy: bool = True
    # model compute/collective overlap (False = serial like the fork's
    # -nccl_allreduce_latency add at main.cc:121)
    overlap_collectives: bool = True
    # sample interval stats every N cycles (reference: gpu_stat_sample_freq)
    stat_sample_cycles: int = 100_000
    # deadlock detection (reference: -gpu_deadlock_detect)
    deadlock_detect: bool = True
    deadlock_cycles: int = 1_000_000_000
    # default trip count for while loops whose bound isn't in the HLO
    default_loop_trip_count: int = 1
    # power model on/off (reference: -power_simulation_enabled)
    power_enabled: bool = False
    # DVFS operating point (reference: AccelWattch DVFS support): voltage/
    # frequency scale applied to the power coefficients; pair with a
    # clock_ghz overlay — power.model.dvfs_overlays builds both
    dvfs_scale: float = 1.0
    # checkpoint/resume at kernel granularity (reference:
    # -checkpoint_kernel / -resume_kernel, abstract_hardware_model.cc:136):
    # resume fast-forwards the first N kernel launches; checkpoint stops
    # the replay after N launches and records the stop point
    resume_kernel: int = 0
    checkpoint_kernel: int = 0
    # sub-kernel checkpoint/resume at ENTRY-OP granularity inside one
    # module replay (reference: per-instruction functional checkpoint,
    # abstract_hardware_model.h:1280-1288).  checkpoint_op=K stops the
    # entry walk after K scheduled ops and drains in-flight transfers (a
    # state snapshot cannot leave DMA mid-flight); resume_op=K
    # fast-forwards the first K ops, treating transfers they started as
    # already complete.  The boundary is therefore a barrier: for a
    # schedule with nothing in flight at op K the two halves partition the
    # full run exactly.
    resume_op: int = 0
    checkpoint_op: int = 0
    # model HBM bandwidth sharing between async DMA and compute (the
    # FR-FCFS/queueing slot of the reference, dram_sched.h:41 — here a
    # fair-share split when both stream concurrently)
    model_hbm_contention: bool = True
    # enforce the vmem capacity budget: when a module pins more S(1) bytes
    # than arch.vmem_bytes, the overflow fraction of vmem traffic is
    # re-priced at HBM bandwidth (spill) — the shmem/L1 capacity analogue
    # (gpu-cache.h adaptive_cache_config)
    model_vmem_capacity: bool = True


# ---------------------------------------------------------------------------
# Field validation metadata (consumed by tpusim.analysis.config_passes)
# ---------------------------------------------------------------------------

#: per-config-path validation classes, declared next to the dataclasses
#: they describe so a new knob gets its rule in the same diff.  Keys are
#: dotted paths relative to a SimConfig; classes:
#:   positive  — must be > 0 and finite (clocks, bandwidths, dimensions)
#:   nonneg    — must be >= 0 and finite (latencies, cycle counts)
#:   fraction  — must be in (0, 1] (efficiencies, achieved-rate scales)
#:   enum:<..> — must be one of the listed values
CONFIG_FIELD_RULES: dict[str, str] = {
    # --- ArchConfig -------------------------------------------------------
    "arch.clock_ghz": "positive",
    "arch.mxu_count": "positive",
    "arch.mxu_rows": "positive",
    "arch.mxu_cols": "positive",
    "arch.mxu_fill_cycles": "nonneg",
    "arch.mxu_weight_stall_cycles": "nonneg",
    "arch.mxu_efficiency": "fraction",
    "arch.mxu_conv_tap_efficiency": "fraction",
    "arch.vpu_sublanes": "positive",
    "arch.vpu_lanes": "positive",
    "arch.vpu_alus": "positive",
    "arch.vpu_transcendental_per_cycle": "positive",
    "arch.vpu_reduce_slowdown": "positive",
    "arch.vpu_lane_cross_cycles": "nonneg",
    "arch.scalar_op_cycles": "nonneg",
    "arch.op_overhead_cycles": "nonneg",
    "arch.gather_row_overhead_cycles": "nonneg",
    "arch.dma_issue_latency": "nonneg",
    "arch.relayout_efficiency": "fraction",
    "arch.relayout_lane_efficiency": "fraction",
    "arch.small_kernel_floor_cycles": "nonneg",
    "arch.vmem_copy_efficiency": "fraction",
    "arch.vmem_slice_efficiency": "fraction",
    "arch.hbm_bandwidth": "positive",
    "arch.hbm_efficiency": "fraction",
    "arch.hbm_latency": "nonneg",
    "arch.hbm_gib": "positive",
    "arch.vmem_bytes": "positive",
    "arch.vmem_bandwidth_mult": "positive",
    "arch.host_bandwidth": "positive",
    "arch.host_latency": "nonneg",
    # --- IciConfig --------------------------------------------------------
    "arch.ici.topology": "enum:torus3d,torus2d,mesh2d,ring",
    "arch.ici.link_bandwidth": "positive",
    "arch.ici.hop_latency": "nonneg",
    "arch.ici.launch_latency": "nonneg",
    "arch.ici.links_per_axis": "positive",
    "arch.ici.efficiency": "fraction",
    "arch.ici.dcn_bandwidth": "positive",
    "arch.ici.dcn_latency": "nonneg",
    "arch.ici.chips_per_slice": "nonneg",
    "arch.ici.dcn_nics_per_slice": "nonneg",
    "arch.ici.dcn_hop_bandwidth": "nonneg",
    "arch.ici.dcn_hop_latency": "nonneg",
    "arch.ici.dcn_oversubscription": "positive",
    "arch.ici.network_mode": "enum:analytic,detailed",
    "arch.ici.packet_bytes": "positive",
    # --- SimConfig --------------------------------------------------------
    "kernel_window": "positive",
    "stat_sample_cycles": "positive",
    "deadlock_cycles": "positive",
    "default_loop_trip_count": "positive",
    "dvfs_scale": "positive",
    "resume_kernel": "nonneg",
    "checkpoint_kernel": "nonneg",
    "resume_op": "nonneg",
    "checkpoint_op": "nonneg",
}


# ---------------------------------------------------------------------------
# Overlay / composition
# ---------------------------------------------------------------------------


def _overlay_dataclass(obj: Any, updates: dict[str, Any]) -> Any:
    """Return a copy of frozen dataclass ``obj`` with ``updates`` applied.
    Nested dataclasses accept nested dicts."""
    kw: dict[str, Any] = {}
    valid = {f.name: f for f in fields(obj)}
    for key, val in updates.items():
        if key not in valid:
            raise KeyError(
                f"unknown config key {key!r} for {type(obj).__name__}; "
                f"valid: {sorted(valid)}"
            )
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur) and isinstance(val, dict):
            kw[key] = _overlay_dataclass(cur, val)
        elif isinstance(cur, dict) and isinstance(val, dict):
            merged = dict(cur)
            merged.update(val)
            kw[key] = merged
        else:
            kw[key] = val
    return dataclasses.replace(obj, **kw)


def overlay(config: Any, *layers: dict[str, Any]) -> Any:
    """Apply overlay dicts in order — the ``append_gpgpusim_config`` pattern
    (later layers win)."""
    for layer in layers:
        config = _overlay_dataclass(config, layer)
    return config


def parse_flag_file(path: str | Path) -> dict[str, Any]:
    """Parse a reference-style flag file (``-key value`` lines, ``#``/``//``
    comments) into an overlay dict.  Dotted keys reach nested configs:
    ``-arch.ici.link_bandwidth 9e10``."""
    updates: dict[str, Any] = {}
    for raw in Path(path).read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("//"):
            continue
        if not line.startswith("-"):
            continue
        key, _, val = line[1:].partition(" ")
        val = val.strip()
        parsed: Any
        try:
            parsed = json.loads(val)
        except (json.JSONDecodeError, ValueError):
            parsed = val
        node = updates
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = parsed
    return updates


def tuned_overlay_path(arch_name: str) -> Path | None:
    """Locate the committed tuner overlay for an arch, if one exists.

    The tuner (``tpusim.harness.tuner``) writes silicon-fitted parameters
    to ``configs/<arch>.tuned.flags`` — the analogue of the reference's
    ``tested-cfgs`` produced by ``util/tuner/tuner.py:23-67`` and
    re-validated every CI run.  ``$TPUSIM_TUNED_DIR``, when set, is the
    EXCLUSIVE source (tests point it at an empty dir to isolate from repo
    artifacts); otherwise the repo-root ``configs/`` directory is used."""
    import os

    env = os.environ.get("TPUSIM_TUNED_DIR")
    base = (
        Path(env) if env
        else Path(__file__).resolve().parents[2] / "configs"
    )
    p = base / f"{arch_name.lower()}.tuned.flags"
    if p.is_file():
        return p
    # no silicon of this generation was ever measured here: fall back to
    # the cross-generation derivation (silicon-calibrated transferable
    # fractions/cycle-counts of the shared TensorCore design applied over
    # this generation's published absolutes — tpusim.timing.derive)
    d = base / f"{arch_name.lower()}.derived.flags"
    return d if d.is_file() else None


def load_config(
    base: "SimConfig | None" = None,
    *,
    arch: str | None = None,
    overlays: list[dict[str, Any] | str | Path] | None = None,
    tuned: bool = True,
) -> SimConfig:
    """Compose a SimConfig: named arch preset + the committed tuner
    overlay for that arch (when present and ``tuned=True``) + overlay
    dicts / flag files / JSON files, in order.  Explicit overlays win
    over the tuned values."""
    from tpusim.timing.arch import arch_preset

    cfg = base or SimConfig()
    if arch is not None:
        cfg = dataclasses.replace(cfg, arch=arch_preset(arch))
        if tuned:
            tp = tuned_overlay_path(arch)
            if tp is not None:
                cfg = overlay(cfg, parse_flag_file(tp))
    for item in overlays or []:
        if isinstance(item, (str, Path)):
            p = Path(item)
            if p.suffix == ".json":
                layer = json.loads(p.read_text())
            else:
                layer = parse_flag_file(p)
        else:
            layer = item
        cfg = overlay(cfg, layer)
    return cfg
