"""Per-op / per-fusion cost model.

The TPU rebuild of the reference's opcode→unit/latency machinery: the
``ISA_Def`` opcode maps (``volta_opcode.h``), the ``trace.config`` latency
tables (``trace_config::set_latency``, ``trace_driven.cc:385-480``), and the
memory coalescer (``warp_inst_t::generate_mem_accesses``,
``abstract_hardware_model.cc:284``).  Where the reference routes each SASS
opcode to SP/DP/INT/SFU/TENSOR pipelines with fixed latencies, we route each
HLO op to MXU/VPU/scalar/transpose/DMA/ICI and compute a roofline time from
its actual shapes:

    cycles = overhead + max(compute_cycles, hbm_bytes / hbm_bytes_per_cycle)

MXU compute time uses a systolic-pass model (fill/drain + streamed rows,
tiles distributed over the MXUs); fusions are costed by walking their called
computations — the analogue of the per-fusion problem called out as the
"hard part" in SURVEY.md §7.
"""

from __future__ import annotations

import itertools
import math
import re
from dataclasses import dataclass, field

from tpusim.ir import (
    Computation,
    FREE_OPCODES,
    ModuleTrace,
    TensorSpec,
    TraceOp,
    Unit,
    dtype_bytes,
    leaves_of,
)
from tpusim.timing.config import ArchConfig

__all__ = ["OpCost", "CostModel", "classify_bound", "dot_dims", "conv_dims",
           "shape_memory_bytes", "while_trip_count"]


# ---------------------------------------------------------------------------
# Opcode categories (the ISA_Def tables)
# ---------------------------------------------------------------------------

TRANSCENDENTAL_OPS = frozenset({
    "exponential", "exponential-minus-one", "log", "log-plus-one", "tanh",
    "sqrt", "rsqrt", "cbrt", "power", "sine", "cosine", "tan", "atan2",
    "erf", "logistic", "divide", "remainder",
})

ELEMENTWISE_OPS = frozenset({
    "add", "subtract", "multiply", "maximum", "minimum", "and", "or", "xor",
    "not", "negate", "abs", "sign", "compare", "select", "clamp", "floor",
    "ceil", "round-nearest-afz", "round-nearest-even", "convert",
    "is-finite", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "popcnt", "count-leading-zeros", "stochastic-convert",
    "real", "imag", "complex", "map", "reduce-precision",
})

DATA_MOVEMENT_OPS = frozenset({
    "copy", "reshape", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "pad", "reverse", "broadcast", "iota", "gather",
    "scatter", "set-dimension-size",
})

REDUCE_OPS = frozenset({"reduce", "reduce-window", "select-and-scatter"})

#: XLA:TPU internal custom-calls that are aliasing views or compiler
#: hints — zero device time (all three observed at ~0ns on v5e silicon;
#: the model was charging launch overhead + a full memory roofline)
FREE_CUSTOM_CALL_TARGETS = frozenset({
    "ConcatBitcast", "AllocateBuffer", "AssumeGatherIndicesInBound",
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
})

#: ops whose cost is set by the moved region, not the full buffers
_REGION_OPS = frozenset({
    "slice", "dynamic-slice", "dynamic-update-slice", "gather", "scatter",
})

_TRIP_COUNT_RE = re.compile(r'known_trip_count[^0-9]*?(\d+)')
_INDUCTION_RE = re.compile(r'known_induction_variable')

# Mosaic/Pallas custom-call cost estimates in backend_config:
# {"custom_call_config": {"cost_estimate": {"flops": N,
#  "transcendentals": N, "bytes_accessed": N}}}
_CE_FLOPS_RE = re.compile(r'"flops"\s*:\s*"?([0-9.eE+]+)')
_CE_TRANS_RE = re.compile(r'"transcendentals"\s*:\s*"?([0-9.eE+]+)')
_CE_BYTES_RE = re.compile(r'"bytes_accessed"\s*:\s*"?([0-9.eE+]+)')


def _parse_cost_estimate(
    backend_config: str,
) -> tuple[float, float, float] | None:
    """(flops, transcendentals, bytes_accessed) from a Mosaic/Pallas
    ``cost_estimate``, or None when absent."""
    if "cost_estimate" not in backend_config:
        return None
    f = _CE_FLOPS_RE.search(backend_config)
    t = _CE_TRANS_RE.search(backend_config)
    b = _CE_BYTES_RE.search(backend_config)
    if not (f or t or b):
        return None
    return (
        float(f.group(1)) if f else 0.0,
        float(t.group(1)) if t else 0.0,
        float(b.group(1)) if b else 0.0,
    )


# ---------------------------------------------------------------------------
# Structured attr helpers
# ---------------------------------------------------------------------------


def _int_set(attrs: dict[str, str], key: str) -> tuple[int, ...]:
    val = attrs.get(key, "")
    val = val.strip().strip("{}")
    return tuple(int(x) for x in val.split(",") if x.strip())


def dot_dims(
    op: TraceOp, comp: Computation
) -> tuple[int, int, int, int, str]:
    """(batch, M, N, K, dtype) of a dot, from its operand shapes + dims."""
    lhs = _leaf_shape(comp, op.operands[0])
    rhs = _leaf_shape(comp, op.operands[1])
    lc = _int_set(op.attrs, "lhs_contracting_dims")
    rc = _int_set(op.attrs, "rhs_contracting_dims")
    lb = _int_set(op.attrs, "lhs_batch_dims")
    rb = _int_set(op.attrs, "rhs_batch_dims")
    b = math.prod(lhs.shape[i] for i in lb) if lb else 1
    k = math.prod(lhs.shape[i] for i in lc) if lc else 1
    m = math.prod(
        d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb
    ) if lhs.shape else 1
    n = math.prod(
        d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb
    ) if rhs.shape else 1
    return b, m, n, k, lhs.dtype


_WINDOW_FIELD_RES = {
    "size": re.compile(r"size=([0-9x]+)"),
    "stride": re.compile(r"stride=([0-9x]+)"),
    "pad": re.compile(r"pad=([0-9_x\-]+)"),
    "lhs_dilate": re.compile(r"lhs_dilate=([0-9x]+)"),
    "rhs_dilate": re.compile(r"rhs_dilate=([0-9x]+)"),
}


def _parse_window(window: str, ndims: int) -> dict[str, list]:
    """Per-spatial-dim window fields with XLA defaults filled in."""
    out: dict[str, list] = {}
    for key, rx in _WINDOW_FIELD_RES.items():
        m = rx.search(window)
        if not m:
            continue
        if key == "pad":
            pairs = []
            for part in m.group(1).split("x"):
                lo, _, hi = part.partition("_")
                pairs.append((int(lo or 0), int(hi or 0)))
            out[key] = pairs
        else:
            out[key] = [int(d) for d in m.group(1).split("x")]
    n = len(out.get("size", [])) or ndims
    out.setdefault("size", [1] * n)
    out.setdefault("stride", [1] * n)
    out.setdefault("pad", [(0, 0)] * n)
    out.setdefault("lhs_dilate", [1] * n)
    out.setdefault("rhs_dilate", [1] * n)
    return out


def _avg_real_taps(
    in_size: int, out_size: int, k: int, stride: int,
    pad_low: int, lhs_dil: int, rhs_dil: int,
) -> float:
    """Average number of kernel taps per output position that land on a
    *real* input element — i.e. in bounds and not on a dilation hole.

    XLA:TPU lowers batched matmuls to ``convolution-base-dilated`` with
    stride/dilation chosen so each output position touches exactly one real
    element per spatial dim (observed: ``size=4x8 stride=4x8 pad=3_3x7_7
    lhs_dilate=3x7`` on a [4,...,8,...] batch grid).  Charging the full
    ``prod(size)`` kernel there overstates FLOPs 32× (round-3 silicon,
    attention +3169%).  Exact counting prices both true convs (where edge
    padding trims a little) and these degenerate matmul encodings."""
    if k <= 1 or in_size <= 0 or out_size <= 0:
        return 1.0
    if (
        lhs_dil <= 1 and rhs_dil <= 1 and pad_low == 0
        and (out_size - 1) * stride + k <= in_size
    ):
        return float(k)  # interior-only fast path: every tap is real
    # sample output positions when the grid is large; tap pattern is
    # periodic in stride/dilate so a prefix is representative
    sample = range(out_size) if out_size <= 4096 else range(4096)
    total = 0
    for j in sample:
        base = j * stride - pad_low
        for p in range(k):
            pos = base + p * rhs_dil
            if pos < 0:
                continue
            if pos % lhs_dil:
                continue
            if pos // lhs_dil >= in_size:
                continue
            total += 1
    return max(total / len(sample), 1e-6)


def conv_dims(
    op: TraceOp, comp: Computation
) -> tuple[int, int, int, int, str]:
    """Convolution as an implicit matmul: (batch=1, M, N, K, dtype) with
    M = output spatial positions × batch, N = output features,
    K = effective real kernel taps × input features / feature_groups.

    "Effective real taps" counts only kernel positions that hit in-bounds,
    non-dilation-hole input elements (see :func:`_avg_real_taps`) — this is
    what makes XLA's matmul-as-dilated-conv lowering price like the matmul
    it is."""
    rhs = _leaf_shape(comp, op.operands[1])
    lhs = _leaf_shape(comp, op.operands[0])
    out = leaves_of(op.result)[0]
    dim_labels = op.attrs.get("dim_labels", "")
    fgc = int(op.attrs.get("feature_group_count", "1") or 1)
    bgc = int(op.attrs.get("batch_group_count", "1") or 1)

    in_feat = out_feat = None
    lhs_spatial: dict[int, int] = {}
    out_spatial: dict[int, int] = {}
    if "_" in dim_labels and "->" in dim_labels:
        lhs_labels, rest = dim_labels.split("_", 1)
        rhs_labels, out_labels = rest.split("->", 1)
        for pos, ch in enumerate(rhs_labels):
            if ch == "i" and pos < len(rhs.shape):
                in_feat = rhs.shape[pos]
            elif ch == "o" and pos < len(rhs.shape):
                out_feat = rhs.shape[pos]
        for pos, ch in enumerate(lhs_labels):
            if ch.isdigit() and pos < len(lhs.shape):
                lhs_spatial[int(ch)] = lhs.shape[pos]
        for pos, ch in enumerate(out_labels):
            if ch.isdigit() and pos < len(out.shape):
                out_spatial[int(ch)] = out.shape[pos]
    if out_feat is None:
        out_feat = out.shape[-1] if out.shape else 1
    if in_feat is None:
        in_feat = rhs.shape[-2] if len(rhs.shape) >= 2 else 1

    w = _parse_window(op.attrs.get("window", ""), len(lhs_spatial))
    taps = 1.0
    for d, k_sz in enumerate(w["size"]):
        if d not in lhs_spatial or d not in out_spatial:
            # unparseable dim_labels: charge the full kernel extent (the
            # conservative pre-round-4 behavior) rather than collapsing
            # the spatial factor to 1
            taps *= max(float(k_sz), 1.0)
            continue
        taps *= _avg_real_taps(
            lhs_spatial[d], out_spatial[d], k_sz,
            w["stride"][d] if d < len(w["stride"]) else 1,
            w["pad"][d][0] if d < len(w["pad"]) else 0,
            w["lhs_dilate"][d] if d < len(w["lhs_dilate"]) else 1,
            w["rhs_dilate"][d] if d < len(w["rhs_dilate"]) else 1,
        )
    m = max(out.elems // max(out_feat, 1), 1)
    k = max(int(round(taps * in_feat)) // max(fgc * bgc, 1), 1)
    return 1, m, out_feat, k, lhs.dtype


def while_trip_count(op: TraceOp, default: int = 1) -> int:
    """Trip count of a while op, from XLA's ``known_trip_count`` backend
    config when present (lax.scan/fori_loop produce it)."""
    bc = op.attrs.get("backend_config", "")
    m = _TRIP_COUNT_RE.search(bc)
    if m:
        return int(m.group(1))
    return default


def _is_free_custom_call(op: TraceOp) -> bool:
    """XLA:TPU marker custom-calls (aliasing views / compiler hints) —
    zero device time, no memory traffic."""
    return (
        op.base == "custom-call"
        and op.attrs.get("custom_call_target", "").strip('"')
        in FREE_CUSTOM_CALL_TARGETS
    )


def _result_leaf(op: TraceOp) -> TensorSpec | None:
    """Largest leaf of an op's result (the shape a VPU op iterates)."""
    leaves = leaves_of(op.result)
    if not leaves:
        return None
    return max(leaves, key=lambda l: l.nbytes)


def _leaf_shape(comp: Computation, operand: str) -> TensorSpec:
    """Resolve an operand name to its (first leaf) TensorSpec."""
    if comp.has_op(operand):
        leaves = leaves_of(comp.op(operand).result)
        if leaves:
            return leaves[0]
    return TensorSpec("f32", ())


def _operand_bytes(comp: Computation, op: TraceOp) -> int:
    total = 0
    seen = set()
    for name in op.operands:
        if name in seen:
            continue
        seen.add(name)
        if comp.has_op(name):
            total += comp.op(name).result.nbytes
    return total


def _region_bytes(comp: Computation, op: TraceOp) -> float:
    """Bytes actually moved by a slice-like op: read + write of the
    region.  For dynamic-update-slice the region is the update operand;
    for the others it's the result."""
    if op.base == "dynamic-update-slice" and len(op.operands) >= 2:
        region = _leaf_shape(comp, op.operands[1]).nbytes
    else:
        region = sum(l.nbytes for l in leaves_of(op.result))
    return 2.0 * region


def _fusion_param_region_bytes(
    called: Computation,
) -> dict[int, float]:
    """For a fused computation, map parameter index → bytes actually read,
    for parameters consumed ONLY through slice-like ops.  Scanned loop
    bodies fuse ``dynamic-slice(stacked_weights, iv)`` — charging the full
    stacked tensor would overstate a per-layer read by the layer count."""
    consumers: dict[str, list[TraceOp]] = {}
    for inner in called.ops:
        for o in inner.operands:
            consumers.setdefault(o, []).append(inner)
    out: dict[int, float] = {}
    for pop in called.ops:
        if pop.opcode != "parameter":
            continue
        try:
            idx = int(pop.attrs.get("param_index", ""))
        except ValueError:
            continue
        cons = consumers.get(pop.name, [])
        if cons and all(c.base in _REGION_OPS for c in cons):
            # _region_bytes counts read+write of the moved region; the
            # parameter side contributes the read half
            out[idx] = float(sum(
                _region_bytes(called, c) / 2.0 for c in cons
            ))
    return out


_CHASE_THROUGH = ("bitcast", "bitcast-convert", "copy", "convert", "reshape")


def _is_relayout(src: TensorSpec | None, dst: TensorSpec | None) -> bool:
    """True when a copy physically rearranges data.  A missing layout
    annotation means default minor-to-major, so ``None`` must compare
    equal to the explicit default (and an unannotated tiling must not
    make a plain copy look like a transpose)."""
    if src is None or dst is None:
        return False
    default = tuple(range(len(src.shape) - 1, -1, -1))
    src_layout = src.layout if src.layout is not None else default
    dst_layout = dst.layout if dst.layout is not None else (
        tuple(range(len(dst.shape) - 1, -1, -1))
    )
    if src_layout != dst_layout:
        return True
    if src.tiling is None or dst.tiling is None:
        return False
    return src.tiling != dst.tiling


def _minor_dim_size(spec: TensorSpec) -> int:
    """Size of the minor-most (lane) dimension under the buffer's layout
    (layout tuples are minor-to-major; absent layout = default)."""
    if not spec.shape:
        return 0
    minor = spec.layout[0] if spec.layout else len(spec.shape) - 1
    if 0 <= minor < len(spec.shape):
        return int(spec.shape[minor])
    return 0


def _is_lane_preserving_relayout(
    src: TensorSpec | None, dst: TensorSpec | None,
) -> bool:
    """A relayout whose minor dims are dense multiples of the 128-lane
    tile on BOTH sides only reorders whole tiles (contiguous 256B+ runs
    for bf16) — it streams near plain-copy rate, unlike a sub-lane
    shuffle that gathers at element granularity.  A tiling (packing)
    change shuffles elements WITHIN sublanes regardless of dim sizes —
    always the slow class."""
    if src is None or dst is None:
        return False
    if src.tiling != dst.tiling:
        return False
    s, d = _minor_dim_size(src), _minor_dim_size(dst)
    return s > 0 and d > 0 and s % 128 == 0 and d % 128 == 0


def _is_movement_fusion(module: ModuleTrace, comp_name: str) -> bool:
    """True when a fused computation contains only data-movement ops
    (slice/DUS/concat/copy/...) — it is a DMA-style move, not compute."""
    if comp_name not in module.computations:
        return False
    comp = module.computation(comp_name)
    cached = getattr(comp, "_is_movement_cache", None)
    if cached is not None:
        return cached
    ok = True
    for inner in comp.ops:
        if inner.opcode in FREE_OPCODES or inner.base in FREE_OPCODES:
            continue
        if inner.base not in DATA_MOVEMENT_OPS:
            ok = False
            break
    try:
        comp._is_movement_cache = ok
    except (AttributeError, TypeError):
        pass
    return ok


def _fusion_dus_views(
    called: Computation,
) -> tuple[float | None, dict[int, float]]:
    """One walk over a fused computation's root elements producing both
    DUS-aliasing views:

    * a RESULT write cap — if any output is a dynamic-update-slice into a
      carried buffer (the activation-stash pattern in scanned training
      loops), the written bytes are the update region, siblings in a
      mixed tuple (the lstm cell's ``(stash, h, c)``) their own full
      size, and parameter pass-throughs zero.  ``None`` when no DUS (and
      not all-aliased): no cap applies — EXCEPT the all-passthrough case
      (every element a parameter alias), which caps at 0.0 exactly as it
      did before DUS handling existed.
    * PARAM read caps — XLA aliases a DUS's destination operand onto the
      output: the kernel reads the update region (tile-granular RMW),
      not the whole carried buffer (lstm: a 128KB update into an 8.4MB
      carry read +219% before).  A parameter is only capped when ALL its
      consumers are on the DUS-destination chase chain — a sibling op
      reading the full buffer (e.g. ``(dus(p0, upd), reduce(p0))``)
      keeps the full charge."""
    root = called.root
    elements = [root]
    if root.base == "tuple":
        elements = [
            called.op(o) for o in root.operands if called.has_op(o)
        ]

    consumers: dict[str, set[str]] = {}
    for inner in called.ops:
        for o in inner.operands:
            consumers.setdefault(o, set()).add(inner.name)

    total = 0.0
    found_dus = False
    found_other = False
    param_caps: dict[int, float] = {}
    for el in elements:
        seen = 0
        while el.base in _CHASE_THROUGH and el.operands and seen < 8:
            if not called.has_op(el.operands[0]):
                break
            el = called.op(el.operands[0])
            seen += 1
        if el.base == "dynamic-update-slice" and len(el.operands) >= 2:
            region = float(_leaf_shape(called, el.operands[1]).nbytes)
            total += region
            found_dus = True
            # chase the DUS destination back to the fusion parameter it
            # aliases (possibly through bitcasts), remembering the chain
            chain = {el.name}
            dest = el.operands[0]
            hops = 0
            while called.has_op(dest) and hops < 8:
                dop = called.op(dest)
                if dop.opcode == "parameter":
                    try:
                        idx = int(dop.attrs.get("param_index", ""))
                    except ValueError:
                        break
                    if consumers.get(dop.name, set()) <= chain:
                        param_caps[idx] = min(
                            param_caps.get(idx, float("inf")), region
                        )
                    break
                if dop.base in _CHASE_THROUGH and dop.operands:
                    # every intermediate view on the chase chain must be
                    # consumed only by the chain itself: a bitcast that
                    # also feeds a sibling (e.g. ``reduce(bitcast(p0))``)
                    # means the kernel reads the FULL buffer through that
                    # sibling, and capping the parameter at the update
                    # region would hide the traffic
                    if not consumers.get(dop.name, set()) <= chain:
                        break
                    chain.add(dop.name)
                    dest = dop.operands[0]
                    hops += 1
                else:
                    break
        elif el.opcode == "parameter":
            continue  # pass-through alias, no write
        else:
            # computed output: its own full size (caps to identity when
            # it stands beside a DUS in a mixed tuple)
            total += float(sum(l.nbytes for l in leaves_of(el.result)))
            found_other = True
    if found_dus or not found_other:
        return total, param_caps
    return None, param_caps


#: a "small" standalone kernel: moved region up to 32KB — eight (8,128)
#: f32 tiles — or a (near-)scalar result.  (The 2x factor at the use
#: site mirrors the read+write doubling ``_region_bytes`` applies, so
#: the cutoff is on the ONE-SIDED region.)  The fixture evidence
#: brackets the band rather than sampling inside it: [1,1] slices ran
#: 229-567ns and the lstm 8KB loop copies 1.57us on v5e — all
#: launch/latency-dominated — and even a 32KB-region move at stream
#: rate (~64KB of traffic / ~1100 B/cy ~= 60 cycles) sits far below the
#: ~700-cycle dispatch floor, so the floor is the binding price through
#: the whole band; the ``max`` in the floor application keeps genuinely
#: streaming-bound kernels roofline-priced.  No committed fixture row
#: falls between 8KB and 32KB to discriminate further — revisit when
#: one lands.
_SMALL_KERNEL_REGION_BYTES = 32 * 1024
_SMALL_KERNEL_RESULT_BYTES = 1024


def _is_small_standalone_kernel(op: TraceOp, comp: Computation) -> bool:
    """Sub-tile data movement (bare slice/DS/DUS) or a (near-)scalar
    reduce/fusion: kernels whose device duration is dominated by the
    fixed dispatch floor, not the roofline (v5e: [1,1] slices 229-567ns,
    scalar reduce-fusion 329ns, one-row DUS 594ns vs a ~5ns roofline)."""
    if op.base in ("slice", "dynamic-slice", "dynamic-update-slice"):
        return _region_bytes(comp, op) <= 2.0 * _SMALL_KERNEL_REGION_BYTES
    if op.base in ("fusion", "reduce"):
        return (
            sum(l.nbytes for l in leaves_of(op.result))
            <= _SMALL_KERNEL_RESULT_BYTES
        )
    return False


def _memory_bytes(
    comp: Computation,
    op: TraceOp,
    module: ModuleTrace | None = None,
) -> tuple[float, float]:
    """(hbm_bytes, vmem_bytes) touched by one op: operands + result, split
    by the layout's memory space.  XLA:TPU marks vmem-pinned buffers with
    ``S(1)`` in the layout (observed on loop carries XLA keeps resident in
    the 128MB vmem); default space 0 is HBM.  For fusions, parameters that
    are only sliced inside are charged at the sliced size."""
    hbm = 0.0
    vmem = 0.0
    seen = set()

    region_by_index: dict[int, float] = {}
    result_cap: float | None = None
    if op.base == "fusion" and op.called and module is not None:
        if op.called[0] in module.computations:
            called = module.computation(op.called[0])
            region_by_index = _fusion_param_region_bytes(called)
            result_cap, dus_caps = _fusion_dus_views(called)
            for idx, cap in dus_caps.items():
                prev = region_by_index.get(idx)
                region_by_index[idx] = (
                    cap if prev is None else min(prev, cap)
                )

    def account(spec, cap: float | None = None) -> None:
        nonlocal hbm, vmem
        total = sum(l.nbytes for l in leaves_of(spec))
        scale = 1.0
        if cap is not None and total > 0:
            scale = min(cap / total, 1.0)
        for leaf in leaves_of(spec):
            if leaf.memory_space != 0:
                vmem += leaf.nbytes * scale
            else:
                hbm += leaf.nbytes * scale

    for i, name in enumerate(op.operands):
        if name in seen or not comp.has_op(name):
            continue
        seen.add(name)
        account(comp.op(name).result, region_by_index.get(i))
    account(op.result, result_cap)
    return hbm, vmem


def shape_memory_bytes(
    comp: Computation,
    op: TraceOp,
    module: ModuleTrace | None = None,
) -> tuple[float, float]:
    """Public view of the operand+result byte accounting: the
    ``(hbm_bytes, vmem_bytes)`` an op's *shapes* imply, before any
    kernel-declared ``cost_estimate`` override or region capping.  The
    perf analyzer (:mod:`tpusim.analysis.critpath`) compares this
    shape-derived traffic against the priced traffic to catch kernels
    whose own accounting contradicts their roofline (TL503)."""
    return _memory_bytes(comp, op, module)


# ---------------------------------------------------------------------------
# Cost record
# ---------------------------------------------------------------------------


@dataclass
class OpCost:
    """Timing + accounting for one scheduled op."""

    cycles: float = 0.0
    compute_cycles: float = 0.0
    mem_cycles: float = 0.0
    unit: Unit = Unit.NONE
    flops: float = 0.0
    mxu_flops: float = 0.0
    transcendentals: float = 0.0
    hbm_bytes: float = 0.0
    vmem_bytes: float = 0.0
    ici_bytes: float = 0.0
    is_async: bool = False
    #: achieved-rate scale factors per memory port (copies/relayouts/
    #: movement fusions run below the streaming roofline); every
    #: mem_cycles computation — including the engine's spill and
    #: contention repricing — must honor them
    hbm_rate_scale: float = 1.0
    vmem_rate_scale: float = 1.0
    #: bytes_accessed from a kernel's own cost estimate (-1 = none)
    est_bytes: float = -1.0
    #: True when a recursion-depth cutoff clipped part of this total —
    #: such totals are incomplete and must not be memoized
    truncated: bool = False

    def add_compute(self, other: "OpCost") -> None:
        self.compute_cycles += other.compute_cycles
        self.flops += other.flops
        self.mxu_flops += other.mxu_flops
        self.transcendentals += other.transcendentals
        self.truncated = self.truncated or other.truncated


def classify_bound(cost: OpCost, arch: ArchConfig) -> str:
    """Roofline classification of one priced op from the cost model's own
    term breakdown: which resource pins the op's cycles.

    Returns one of ``"ici"`` (collective), ``"none"`` (free), ``"hbm"`` /
    ``"vmem"`` (memory-bound, split by which port's stream time won the
    roofline max), ``"mxu"`` / ``"vpu"`` (compute-bound, split by unit),
    or ``"overhead"`` (issue overhead dominates both terms).  This is the
    term arithmetic the engine itself prices with — the perf analyzer's
    TL503 roofline check must not re-derive it differently."""
    if cost.unit is Unit.ICI:
        return "ici"
    if cost.cycles <= 0:
        return "none"
    if cost.mem_cycles > cost.compute_cycles:
        hbm_t = cost.hbm_bytes / (
            arch.hbm_bytes_per_cycle * max(cost.hbm_rate_scale, 1e-6)
        )
        vmem_t = cost.vmem_bytes / (
            arch.vmem_bytes_per_cycle * max(cost.vmem_rate_scale, 1e-6)
        )
        return "hbm" if hbm_t >= vmem_t else "vmem"
    if cost.compute_cycles > 0:
        return "mxu" if (cost.mxu_flops > 0 or cost.unit is Unit.MXU) else "vpu"
    return "overhead"


# ---------------------------------------------------------------------------
# The model
# ---------------------------------------------------------------------------


@dataclass
class CostModel:
    arch: ArchConfig
    #: per-custom-call-target achieved-FLOP/s override (e.g. pallas kernels)
    custom_call_flops: dict[str, float] = field(default_factory=dict)
    #: unique, never-reused token for this model instance — fusion-cost
    #: cache keys use it so entries can't alias across models with
    #: different arch parameters (an id() would be reusable after GC).
    #: init=False/compare=False: dataclasses.replace/copy must mint a
    #: fresh token, and tokens must not break CostModel equality
    _cache_token: int = field(
        default_factory=itertools.count().__next__,
        init=False, compare=False, repr=False,
    )

    # -- MXU systolic-pass model ------------------------------------------

    def _normalize_matmul_dtype(
        self, dt: str, module: "ModuleTrace | None",
    ) -> str:
        """Undo the capture backend's float normalization for MXU pricing.

        AOT capture on the CPU mesh (the only option for ahead-of-silicon
        multi-chip graphs) runs XLA:CPU's FloatNormalization pass, which
        upcasts every bf16 dot/conv to f32 — pricing those at the f32
        multi-pass rate (0.25x) read a Llama-7B train step at 3.5% MFU.
        On TPU the same program keeps bf16 MXU operands with f32
        accumulation at full rate.  When a CPU-captured module's entry
        parameters are predominantly sub-f32 (the model's declared
        compute dtype) and a matmul reads f32, price it at the
        parameter dtype.  Gated on the capture platform: a TPU-captured
        trace's f32 dot is a genuine precision choice (e.g. an f32
        logits matmul) and keeps the f32 multi-pass rate."""
        if dt != "f32" or module is None:
            return dt
        if module.meta.get("platform") not in ("cpu", "interpreter"):
            return dt
        cached = getattr(module, "_param_dtype_cache", None)
        if cached is None:
            by_dtype: dict[str, float] = {}
            entry = module.entry if module.entry_name else None
            if entry is not None:
                for op in entry.ops:
                    if op.opcode != "parameter":
                        continue
                    for leaf in leaves_of(op.result):
                        by_dtype[leaf.dtype] = (
                            by_dtype.get(leaf.dtype, 0.0) + leaf.nbytes
                        )
            total = sum(by_dtype.values())
            major = max(by_dtype, key=by_dtype.get) if by_dtype else ""
            cached = (
                major
                if total > 0 and by_dtype.get(major, 0) > 0.5 * total
                else ""
            )
            try:
                module._param_dtype_cache = cached
            except (AttributeError, TypeError):
                pass
        if cached in ("bf16", "f16", "bfloat16", "float16"):
            return cached
        return dt

    def mxu_cycles(self, b: int, m: int, n: int, k: int, dtype: str) -> float:
        """Cycles for a (possibly batched) matmul on the MXU array.

        The K dimension maps to the systolic rows, N to the columns, M rows
        stream through; tiles are distributed across the ``mxu_count``
        arrays.  Weight tiles double-buffer: pass i+1's weights load while
        pass i streams, so consecutive passes pipeline and the fill/drain
        latency is paid once per op, not once per pass — charging it per
        pass overstated small-m matmuls 2.4x (lstm_layer round-3 silicon,
        +138%).  What survives per pass is the weight-load floor: a pass
        cannot retire faster than its successor's tile loads
        (``mxu_weight_stall_cycles``) — this is what makes small matmuls
        MXU-inefficient, the analogue of the reference's tensor-core
        initiation intervals (``trace.config`` tensor 2,2)."""
        a = self.arch
        passes = b * math.ceil(k / a.mxu_rows) * math.ceil(n / a.mxu_cols)
        m_pad = max(8, math.ceil(m / 8) * 8)
        # two ways to spread the work over the arrays; XLA picks per shape:
        # (a) whole passes to different MXUs — best when passes >> count
        #     and m is small (each MXU loads a fraction of the tiles);
        # (b) split the streamed rows — every MXU runs all passes on an
        #     m/count chunk, which avoids the ceil(passes/count)
        #     quantization that overstated a 5-pass conv on 4 MXUs by 1.6x
        serial_a = math.ceil(passes / a.mxu_count) * max(
            m_pad, a.mxu_weight_stall_cycles
        )
        m_chunk = max(8, math.ceil(m_pad / a.mxu_count / 8) * 8)
        serial_b = passes * max(m_chunk, a.mxu_weight_stall_cycles)
        serial = min(serial_a, serial_b)
        return (serial + a.mxu_fill_cycles) / max(
            a.mxu_dtype_mult(dtype) * a.mxu_efficiency, 1e-6
        )

    def _vpu_cycles(
        self, elem_ops: float, transcendentals: float, util: float = 1.0,
    ) -> float:
        a = self.arch
        util = max(util, 1e-3)
        return (
            elem_ops / (a.vpu_flops_per_cycle * util)
            + transcendentals / (a.vpu_transcendental_per_cycle * util)
        )

    def _vpu_util(self, spec: TensorSpec | None) -> float:
        """Lane/sublane occupancy of a VPU op on this operand/result shape.

        The (8,128) vector registers map the two minor-most dims to
        (sublane, lane); a narrow minor dim strands lanes — decode's
        [8,1024,8] softmax stages run at ~1/16 throughput on silicon
        because dim 8 sits in the 128-lane position.  Bulk shapes
        (minor >= 128) are unaffected."""
        if spec is None or not spec.shape:
            return 1.0
        order = (
            spec.layout if spec.layout is not None
            else tuple(range(spec.rank - 1, -1, -1))
        )
        if not order:
            return 1.0
        lanes = float(self.arch.vpu_lanes)
        subl = float(self.arch.vpu_sublanes)
        if order[0] >= spec.rank:
            return 1.0  # malformed layout: stay neutral, don't penalize
        util = min(1.0, spec.shape[order[0]] / lanes)
        if len(order) > 1 and order[1] < spec.rank:
            util *= min(1.0, spec.shape[order[1]] / subl)
        return util

    # -- per-op compute cost (no memory term) ------------------------------

    def _compute_cost(self, op: TraceOp, comp: Computation,
                      module: ModuleTrace, depth: int = 0) -> OpCost:
        c = OpCost()
        base = op.base
        out_elems = op.result.elems

        if base in FREE_OPCODES or op.opcode in FREE_OPCODES:
            return c

        if base == "dot":
            b, m, n, k, dt = dot_dims(op, comp)
            dt = self._normalize_matmul_dtype(dt, module)
            c.compute_cycles = self.mxu_cycles(b, m, n, k, dt)
            c.flops = c.mxu_flops = 2.0 * b * m * n * k
            c.unit = Unit.MXU
        elif base == "convolution":
            b, m, n, k, dt = conv_dims(op, comp)
            dt = self._normalize_matmul_dtype(dt, module)
            c.compute_cycles = self.mxu_cycles(b, m, n, k, dt)
            w = _parse_window(op.attrs.get("window", ""), 0)
            if any(s > 1 for s in w["size"]) and not any(
                d > 1 for d in w["lhs_dilate"]
            ):
                # a true spatial conv (not XLA's matmul-as-dilated-conv
                # encoding) pays the window emitter's im2col overhead
                c.compute_cycles /= max(
                    self.arch.mxu_conv_tap_efficiency, 1e-6
                )
            c.flops = c.mxu_flops = 2.0 * b * m * n * k
            c.unit = Unit.MXU
        elif base == "fusion" and op.called:
            inner = self.fused_compute_cost(module, op.called[0], depth + 1)
            c.add_compute(inner)
            c.unit = Unit.MXU if inner.mxu_flops > 0 else Unit.VPU
        elif base in TRANSCENDENTAL_OPS:
            c.transcendentals = float(out_elems)
            c.flops = float(out_elems)
            c.compute_cycles = self._vpu_cycles(
                0, c.transcendentals, self._vpu_util(_result_leaf(op)),
            )
            c.unit = Unit.VPU
        elif base in ELEMENTWISE_OPS:
            c.flops = float(out_elems)
            c.compute_cycles = self._vpu_cycles(
                c.flops, 0, self._vpu_util(_result_leaf(op)),
            )
            c.unit = Unit.VPU
        elif base in REDUCE_OPS:
            in_elems = sum(
                _leaf_shape(comp, o).elems for o in op.operands[:1]
            )
            if base == "reduce-window":
                # a windowed reduction streams in O(max(in, out)) work —
                # hardware/XLA keep running extrema/sums; charging
                # in_elems × window_elems priced a 1024-wide softmax max
                # at ~17M fictitious cycles (round-3 silicon, VERDICT #3a)
                c.flops = float(max(in_elems, out_elems))
                slowdown = 1.0
            else:
                c.flops = float(in_elems)
                # the VPU accumulates packed words, so the per-element
                # reduce cost scales with dtype width (v5e silicon:
                # f32 2D-sum at 9.2x elementwise rate, bf16 row-sum at
                # 4.6x); reducing the minor (lane) dimension additionally
                # pays a per-output lane-shuffle tail (decode fixture:
                # a [.,128]->[.] GEMV-style reduce at ~0.7 cy/output)
                spec = (
                    _leaf_shape(comp, op.operands[0]) if op.operands
                    else op.result if isinstance(op.result, TensorSpec)
                    else None
                )
                dt_scale = (
                    dtype_bytes(spec.dtype) / 4.0
                    if spec is not None and spec.dtype else 1.0
                )
                slowdown = self.arch.vpu_reduce_slowdown * dt_scale
                dims = _int_set(op.attrs, "dimensions")
                if dims and spec is not None:
                    minor = (
                        spec.layout[0] if spec.layout
                        else max(spec.rank - 1, 0)
                    )
                    if minor in dims:
                        # lane-dim reduce: within-tile lane shuffle
                        # (decode fixture, extent 128: ~0.7 cy/output),
                        # plus one tree-combine step per doubling of the
                        # lane TILES crossed.  The tree term is the
                        # standard reduction-tree extrapolation — no
                        # committed fixture row exercises extent > 128
                        # yet; the reduce_lane_wide ubench exists to pin
                        # it on the next live run
                        lanes = max(int(self.arch.vpu_lanes), 1)
                        extent = (
                            spec.shape[minor]
                            if minor < len(spec.shape) else lanes
                        )
                        tiles = max(1, -(-int(extent) // lanes))
                        factor = 1.0 + math.ceil(math.log2(tiles))
                        c.compute_cycles += (
                            out_elems
                            * self.arch.vpu_lane_cross_cycles
                            * factor
                        )
            util = self._vpu_util(
                _leaf_shape(comp, op.operands[0]) if op.operands else None
            )
            c.compute_cycles += self._vpu_cycles(c.flops * slowdown, 0, util)
            c.unit = Unit.VPU
        elif base == "transpose":
            c.unit = Unit.TRANSPOSE
            # handled by memory term; transpose unit streams at vector rate
            c.compute_cycles = out_elems / self.arch.vpu_flops_per_cycle
        elif base in DATA_MOVEMENT_OPS:
            c.unit = Unit.DMA
            if base == "gather":
                # gathered rows pay a per-descriptor cost the streaming
                # roofline can't see; recorded as compute so the charge
                # survives fusion aggregation (the gather usually lives
                # inside a fusion whose memory term is operand-level)
                slice_elems = 1
                for d in _int_set(op.attrs, "slice_sizes"):
                    slice_elems *= max(d, 1)
                if slice_elems > 0 and out_elems > 0:
                    rows = max(out_elems // slice_elems, 1)
                    c.compute_cycles = (
                        rows * float(self.arch.gather_row_overhead_cycles)
                    )
            elif base == "scatter" and len(op.operands) >= 2:
                # a scatter's row count is its INDEX count — the result
                # is the whole table, and pricing a descriptor per table
                # element made a llama-7b embedding-gradient scatter
                # read 271ms (should be ~1ms: 16K rows, not 16M elems).
                # Operand order is (op_0..op_{N-1}, indices,
                # upd_0..upd_{N-1}), so the indices sit at the midpoint
                # for ANY variadic arity; verify by integer dtype
                idx_pos = (len(op.operands) - 1) // 2
                idx = _leaf_shape(comp, op.operands[idx_pos])
                if not idx.dtype.startswith(("s", "u")):
                    for o in op.operands:
                        cand = _leaf_shape(comp, o)
                        if cand.dtype.startswith(("s", "u")):
                            idx = cand
                            break
                rows = 1
                for d in idx.shape:
                    rows *= max(int(d), 1)
                # the index-vector dim enumerates COORDINATES of one row,
                # not rows: divide it out.  HLO records it explicitly
                # (``index_vector_dim=K``); K == rank means every element
                # is a scalar row index and nothing is divided out.  Only
                # when the attr is absent fall back to assuming the
                # trailing dim is the coordinate vector.
                try:
                    ivd = int(op.attrs.get("index_vector_dim", ""))
                except ValueError:
                    ivd = -1 if idx.rank >= 2 else None
                if ivd is not None and -idx.rank <= ivd < idx.rank:
                    rows //= max(int(idx.shape[ivd]), 1)
                c.compute_cycles = (
                    max(rows, 1)
                    * float(self.arch.gather_row_overhead_cycles)
                )
        elif base == "sort":
            n_el = float(max(out_elems, 2))
            c.flops = n_el * math.log2(n_el) * 4.0
            c.compute_cycles = self._vpu_cycles(c.flops, 0)
            c.unit = Unit.VPU
        elif base in ("rng", "rng-bit-generator", "rng-get-and-update-state"):
            c.flops = float(out_elems) * 8.0
            c.compute_cycles = self._vpu_cycles(c.flops, 0)
            c.unit = Unit.VPU
        elif base == "custom-call":
            if _is_free_custom_call(op):
                return c
            target = op.attrs.get("custom_call_target", "").strip('"')
            rate = self.custom_call_flops.get(target)
            est = _parse_cost_estimate(op.attrs.get("backend_config", ""))
            if rate and rate > 0:
                # caller recorded achieved FLOP/s for this kernel target
                c.flops = float(out_elems)
                c.compute_cycles = (
                    c.flops / rate * self.arch.clock_hz
                )
                c.unit = Unit.VPU
            elif est is not None:
                # Mosaic/Pallas kernels publish their own cost estimate;
                # price flops on the MXU (pallas matmul kernels are the
                # common case) and transcendentals on the VPU
                flops, trans, est_bytes = est
                c.flops = flops
                c.mxu_flops = flops
                c.transcendentals = trans
                c.compute_cycles = (
                    flops / self.arch.mxu_flops_per_cycle
                    + self._vpu_cycles(0, trans)
                )
                c.est_bytes = est_bytes
                c.unit = Unit.MXU if flops > 0 else Unit.VPU
            else:
                c.unit = Unit.VPU
        elif base in ("infeed", "outfeed", "send", "recv"):
            c.unit = Unit.DMA
        else:
            # unknown compute op: elementwise-cost fallback
            c.flops = float(out_elems)
            c.compute_cycles = self._vpu_cycles(c.flops, 0)
            c.unit = Unit.VPU
        return c

    def fused_compute_cost(
        self, module: ModuleTrace, comp_name: str, depth: int = 0
    ) -> OpCost:
        """Aggregate compute cost of a fused computation (recursive,
        memoized per module+computation — callers only read the result
        via :meth:`OpCost.add_compute`)."""
        if depth > 16:
            return OpCost(truncated=True)
        # cache lives ON the module (unhashable dataclass; the cache dies
        # with the object), keyed by this model's unique token so two
        # CostModels with different configs never share entries
        per_module = getattr(module, "_fusion_cost_cache", None)
        if per_module is None:
            per_module = {}
            try:
                module._fusion_cost_cache = per_module
            except (AttributeError, TypeError):
                per_module = None
        key = (self._cache_token, comp_name)
        if per_module is not None and key in per_module:
            return per_module[key]
        total = OpCost()
        if comp_name not in module.computations:
            return total
        comp = module.computation(comp_name)
        for op in comp.ops:
            inner = self._compute_cost(op, comp, module, depth)
            total.add_compute(inner)
        if per_module is not None and not total.truncated:
            # a depth-clipped subtree total is partial; caching it would
            # serve the undercount to shallow-depth callers forever
            per_module[key] = total
        return total

    # -- full op cost ------------------------------------------------------

    def op_cost(
        self, op: TraceOp, comp: Computation, module: ModuleTrace
    ) -> OpCost:
        """Roofline cost of one scheduled (entry-level) op.  Collectives get
        ``ici_bytes`` filled but no time here — the engine prices them on
        the ICI via the collective model; ``while``/``conditional``/``call``
        get no time here — the engine recurses into their bodies."""
        a = self.arch
        base = op.base

        if base in FREE_OPCODES or op.opcode in FREE_OPCODES:
            return OpCost(unit=Unit.NONE)

        if op.is_collective:
            c = OpCost(unit=Unit.ICI, is_async=op.is_async_start)
            c.ici_bytes = self.collective_payload_bytes(op, comp)
            return c
        if op.is_async_done or base in ("while", "conditional", "call"):
            return OpCost(unit=Unit.NONE)
        if _is_free_custom_call(op):
            return OpCost(unit=Unit.NONE)

        c = self._compute_cost(op, comp, module)
        # roofline over operands + outputs (the standard fusion assumption,
        # SURVEY.md §7), split by memory space: vmem-resident buffers
        # stream at vmem bandwidth, everything else at achieved HBM rate
        c.hbm_bytes, c.vmem_bytes = _memory_bytes(comp, op, module)
        if c.est_bytes >= 0:
            # the kernel's own accounting (Mosaic cost_estimate) supersedes
            # the operand/result approximation
            c.hbm_bytes = c.est_bytes
        if base in _REGION_OPS:
            # slice-like ops touch only the moved region; XLA aliases the
            # untouched remainder in place (a full-buffer charge made a
            # 1-element dynamic-update-slice cost a 64MB stream)
            region = _region_bytes(comp, op)
            c.hbm_bytes = min(c.hbm_bytes, region)
            c.vmem_bytes = min(c.vmem_bytes, region)
        if base == "fusion" and op.called and module is not None:
            if _is_movement_fusion(module, op.called[0]):
                # a fusion that only slices/concats/copies is a DMA-style
                # move: its VMEM side streams at port rate, not at the
                # banked operand-read bandwidth the roofline assumes (the
                # HBM side already has its own achieved-rate derate)
                c.vmem_rate_scale = a.vmem_slice_efficiency
        if base == "copy":
            # a copy moves its payload once; async copy-start results are
            # (src, dst, ctx) tuples, so operand+result charging counts the
            # payload up to 3x.  Cross-port (HBM<->vmem) transfers stream
            # the payload once through each port; same-port copies read and
            # write through the one port (2x payload on it).
            src_leaf = None
            for o in op.operands[:1]:
                if comp.has_op(o):
                    leaves = leaves_of(comp.op(o).result)
                    if leaves:
                        # tuple copies: the biggest leaf is the payload
                        src_leaf = max(leaves, key=lambda l: l.nbytes)
            dst_leaves = leaves_of(op.result)
            dst_leaf = (
                max(dst_leaves, key=lambda l: l.nbytes)
                if dst_leaves else None
            )
            payload = float(
                src_leaf.nbytes if src_leaf is not None
                else (dst_leaf.nbytes if dst_leaf is not None else 0)
            )
            touches_hbm = c.hbm_bytes > 0
            touches_vmem = c.vmem_bytes > 0
            if touches_hbm and touches_vmem:
                c.hbm_bytes = payload
                c.vmem_bytes = payload
            elif touches_vmem:
                c.hbm_bytes = 0.0
                c.vmem_bytes = 2.0 * payload
                # vmem->vmem copies stream through the load/store ports,
                # not the full banked operand-read bandwidth
                c.vmem_rate_scale = a.vmem_copy_efficiency
            else:
                c.hbm_bytes = 2.0 * payload
                c.vmem_bytes = 0.0
            if _is_relayout(src_leaf, dst_leaf):
                # layout change = physical relayout.  Lane-preserving
                # relayouts reorder whole tiles at near-stream rate
                # (decode fixture: 0.66x); sub-lane shuffles gather at
                # element granularity (conv2d fixture: 0.42x)
                eff = (
                    a.relayout_lane_efficiency
                    if _is_lane_preserving_relayout(src_leaf, dst_leaf)
                    else a.relayout_efficiency
                )
                c.hbm_rate_scale = min(c.hbm_rate_scale, eff)
                c.vmem_rate_scale = min(c.vmem_rate_scale, eff)
        c.hbm_rate_scale = max(c.hbm_rate_scale, 1e-6)
        c.vmem_rate_scale = max(c.vmem_rate_scale, 1e-6)
        c.mem_cycles = max(
            c.hbm_bytes / (a.hbm_bytes_per_cycle * c.hbm_rate_scale),
            c.vmem_bytes / (a.vmem_bytes_per_cycle * c.vmem_rate_scale),
        )
        c.cycles = a.op_overhead_cycles + max(c.compute_cycles, c.mem_cycles)
        if (
            a.small_kernel_floor_cycles > 0
            and not op.is_async_start
            and _is_small_standalone_kernel(op, comp)
        ):
            # sub-tile standalone kernels pay dispatch + sublane
            # addressing + scalar writeback regardless of bytes moved
            c.cycles = max(c.cycles, float(a.small_kernel_floor_cycles))
        c.is_async = op.is_async_start
        if op.opcode in ("copy-start",):
            c.unit = Unit.DMA
        return c

    # -- collectives -------------------------------------------------------

    def collective_payload_bytes(self, op: TraceOp, comp: Computation) -> float:
        """Per-participant payload: input bytes for reduce-ish ops, full
        gathered bytes for all-gather (its cost formula expects the output
        size)."""
        base = op.base
        if base in ("all-gather", "collective-broadcast"):
            leaves = leaves_of(op.result)
            return float(max((l.nbytes for l in leaves), default=0))
        inb = _operand_bytes(comp, op)
        if inb:
            return float(inb)
        leaves = leaves_of(op.result)
        return float(max((l.nbytes for l in leaves), default=0))
