"""Cross-generation derivation of a timing overlay.

Only v5e silicon is reachable from this environment, but the north-star
metric names v5p.  The reference ships per-card tuner-built configs
(``gpu-simulator/configs/tested-cfgs/``); with no v5p silicon to tune
against, the honest equivalent is an explicit partition of the
calibrated model into:

* **published absolutes** — per-generation spec values (clock, MXU
  count/shape, HBM bandwidth/capacity, ICI topology/link rate) that the
  presets in :mod:`tpusim.timing.arch` carry and a derivation must NOT
  touch;
* **transferable calibrations** — dimensionless fractions and
  cycle-count constants of the shared TensorCore microarchitecture
  (same 128x128 MXU, (8,128) vmem tile geometry, DMA engine and
  sequencer design across v4/v5e/v5p), fitted on v5e silicon and
  carried across;
* **non-transferable fits** — values that encode a v5e-specific
  physical quantity (the measured v5e clock) and stay home.

``derive_overlay`` applies the committed v5e-calibrated transferables
over the destination preset and writes ``configs/<dst>.derived.flags``,
which ``load_config`` picks up whenever no real ``<dst>.tuned.flags``
exists.  The partition (with per-knob justification) is
:data:`TRANSFERABLE_KNOBS`; the full confidence argument lives in
``docs/V5P.md``.
"""

from __future__ import annotations

from pathlib import Path

__all__ = ["TRANSFERABLE_KNOBS", "NON_TRANSFERABLE_KNOBS", "derive_overlay"]

#: knob -> why it transfers across TensorCore generations.  These are
#: dimensionless efficiencies or cycle counts of mechanisms that are
#: compiler- or microarchitecture-shaped, not process/spec-shaped.
TRANSFERABLE_KNOBS: dict[str, str] = {
    "hbm_efficiency": (
        "achieved fraction of pin bandwidth under streaming access; "
        "memory-controller quality, consistently ~0.8 of spec across "
        "TPU generations (v5e measured 0.835)"
    ),
    "mxu_efficiency": (
        "sustained fraction of the systolic-pass rate on large matmuls; "
        "pipeline-bubble property of the same 128x128 array (v5p has "
        "more MXUs, not different ones)"
    ),
    "mxu_weight_stall_cycles": (
        "double-buffered weight-load floor per pass, a property of the "
        "128x128 array depth shared by v4/v5e/v5p"
    ),
    "mxu_fill_cycles": (
        "systolic fill/drain latency of the same 128-deep array"
    ),
    "mxu_conv_tap_efficiency": (
        "im2col/emitter overhead of spatial convs — an XLA:TPU code-"
        "generation property, not a chip one"
    ),
    "vpu_transcendental_per_cycle": (
        "transcendental issue rate of the 8x128x4 VPU, same vector unit "
        "layout across generations"
    ),
    "vpu_reduce_slowdown": (
        "dtype-width accumulation law of the VPU reduce tree"
    ),
    "vpu_lane_cross_cycles": (
        "lane-shuffle tail per output element for minor-dim reduces; "
        "lane-crossbar property of the shared VPU geometry"
    ),
    "gather_row_overhead_cycles": (
        "per-noncontiguous-row DMA descriptor cost; DMA-engine design "
        "shared across generations"
    ),
    "dma_issue_latency": (
        "async DMA descriptor setup + first byte (seconds); engine "
        "design constant, not bandwidth-dependent"
    ),
    "relayout_efficiency": (
        "sub-lane shuffle rate as a fraction of stream rate; fixed by "
        "the (8,128) tile geometry all generations share"
    ),
    "relayout_lane_efficiency": (
        "tile-reordering relayout fraction, same tile geometry argument"
    ),
    "vmem_copy_efficiency": (
        "vmem load/store port rate as a fraction of banked operand "
        "bandwidth; same vmem design family"
    ),
    "vmem_slice_efficiency": (
        "movement-fusion port fraction, same argument"
    ),
    "op_overhead_cycles": (
        "per-op sequencer dispatch cycles; core sequencer design"
    ),
    "small_kernel_floor_cycles": (
        "sub-tile standalone-kernel dispatch floor in CYCLES (scales "
        "with clock when converted to time, as a dispatch cost should)"
    ),
}

#: calibrated-on-v5e values that must NOT be carried to another
#: generation, with the reason.
NON_TRANSFERABLE_KNOBS: dict[str, str] = {
    "clock_ghz": (
        "v5e silicon measured 1.737 GHz against a 1.67 announced clock; "
        "each generation's published clock stands until its own silicon "
        "says otherwise"
    ),
    "hbm_bandwidth": "published spec absolute per generation",
    "mxu_count": "published spec absolute per generation",
    "dtype_mult": (
        "fitted s8 multiplier rides the preset default table; dtype "
        "ratios are published per generation"
    ),
}


def derive_overlay(
    src_arch: str = "v5e",
    dst_arch: str = "v5p",
    *,
    out_path: str | Path | None = None,
) -> list[str]:
    """Overlay flag lines carrying ``src_arch``'s calibrated transferable
    knobs onto ``dst_arch``'s published preset.  Writes ``out_path`` when
    given (the canonical location is ``configs/<dst>.derived.flags``)."""
    from tpusim.timing.config import load_config

    src = load_config(arch=src_arch).arch      # preset + committed overlay
    dst = load_config(arch=dst_arch, tuned=False).arch

    lines = [
        f"# tpusim cross-generation derivation: {src_arch} -> {dst_arch}",
        "# transferable TensorCore calibrations over published "
        f"{dst_arch} absolutes — see docs/V5P.md and "
        "tpusim/timing/derive.py for the per-knob argument",
    ]
    for knob in sorted(TRANSFERABLE_KNOBS):
        sv = getattr(src, knob)
        if sv == getattr(dst, knob):
            continue  # preset already agrees; keep the file minimal
        if isinstance(sv, int):
            lines.append(f"-arch.{knob} {sv}")
        else:
            lines.append(f"-arch.{knob} {float(sv):.6g}")
    if out_path is not None:
        p = Path(out_path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(lines) + "\n")
    return lines
