"""Schedule-walking timing engine.

The rebuild of the reference's top-level cycle loop (``gpgpu_sim::cycle``,
``gpu-sim.cc:1871-2110``) at HLO granularity.  A TPU TensorCore executes its
scheduled program **sequentially**, with asynchronous DMA and ICI transfers
explicitly bracketed in the HLO as ``*-start`` / ``*-done`` pairs — so rather
than a 4-clock-domain pipeline simulation, the engine walks the schedule
advancing a core clock, runs async transfers on ICI/DMA resource timelines,
and joins at the ``-done`` ops.  This is precisely the compute/collective
overlap the distributed fork could not model (its NCCL latency is added
serially, ``main.cc:121``; SURVEY.md §5 calls this out as the gap to fix).

``while`` bodies (e.g. lax.scan training loops, ring-attention ppermute
chains) are recursed into and multiplied by the trip count XLA records in
``backend_config.known_trip_count``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field

from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.detailed import make_collective_model
from tpusim.ici.topology import Topology, torus_for
from tpusim.obs.hub import NULL_OBS
from tpusim.obs.sampler import CycleWindowSampler
from tpusim.ir import (
    Computation,
    FREE_OPCODES,
    ModuleTrace,
    TraceOp,
    Unit,
    leaves_of,
)
from tpusim.timing.config import SimConfig
from tpusim.timing.cost import CostModel, while_trip_count

__all__ = ["Engine", "EngineResult", "TimelineEvent"]


def _sub_sampler_like(parent: CycleWindowSampler) -> CycleWindowSampler:
    """A fresh sampler for a control-flow body, inheriting the parent's
    PINNED window (``--obs-window-cycles`` must shape intra-loop
    structure too); auto parents get auto children."""
    return CycleWindowSampler(
        parent.window_cycles if parent.pinned else 0.0
    )


@dataclass
class TimelineEvent:
    name: str
    opcode: str
    unit: str
    start_cycle: float
    end_cycle: float


@dataclass
class EngineResult:
    """Counters for one simulated module execution — the equivalent of the
    reference's ~300 ``gpu_print_stat`` counters (``gpu-sim.h:550-579``)."""

    cycles: float = 0.0
    seconds: float = 0.0
    op_count: int = 0
    flops: float = 0.0
    mxu_flops: float = 0.0
    transcendentals: float = 0.0
    hbm_bytes: float = 0.0
    vmem_bytes: float = 0.0
    ici_bytes: float = 0.0
    collective_count: int = 0
    collective_cycles: float = 0.0       # total ICI busy cycles
    exposed_collective_cycles: float = 0.0  # cycles the core waited on ICI
    dma_cycles: float = 0.0
    exposed_dma_cycles: float = 0.0
    # memory-system fidelity counters (VERDICT r1 #4)
    vmem_resident_bytes: float = 0.0     # peak S(1) residency of the module
    vmem_spill_bytes: float = 0.0        # vmem traffic re-priced at HBM rate
    hbm_contention_cycles: float = 0.0   # extra cycles from DMA/compute share
    # failure-detection counters (the deadlock_check analogue,
    # gpu-sim.h:443): trace-corruption signals from the schedule walk
    orphan_async_joins: int = 0     # -done with no matching -start
    unjoined_async: int = 0         # -start never joined before comp end
    unknown_trip_loops: int = 0     # while loops with unresolvable bounds
    worst_case_branches: int = 0    # conditionals timed at their worst arm
    unit_busy_cycles: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    opcode_cycles: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    # per-instruction aggregates (loop bodies scaled by trip count) — the
    # substrate for per-op silicon correlation (correl_mappings.py's
    # per-kernel counters, at HLO-instruction grain)
    per_op_cycles: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    per_op_count: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    per_op_opcode: dict[str, str] = field(default_factory=dict)
    #: instruction names that are async transfer/collective starts — the
    #: exact flag per-op correlation needs (name conventions lie)
    per_op_async: dict[str, bool] = field(default_factory=dict)
    # per-instruction traffic/work (the counter substrate for the
    # counter-level silicon cross-check: achieved GB/s and TFLOP/s per op)
    per_op_hbm_bytes: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    per_op_flops: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    per_op_mxu_flops: dict[str, float] = field(
        default_factory=lambda: defaultdict(float)
    )
    timeline: list[TimelineEvent] = field(default_factory=list)
    #: cycle-window activity series (tpusim.obs.sampler) when the run was
    #: instrumented; None otherwise.  Not merged/scaled — each module run
    #: owns its own series, the driver composes them at launch offsets.
    samples: object | None = None

    # -- derived -----------------------------------------------------------

    @property
    def mxu_utilization(self) -> float:
        busy = self.unit_busy_cycles.get(Unit.MXU.value, 0.0)
        return busy / self.cycles if self.cycles else 0.0

    @property
    def achieved_flops(self) -> float:
        return self.flops / self.seconds if self.seconds else 0.0

    @property
    def hbm_gbps(self) -> float:
        return self.hbm_bytes / self.seconds / 1e9 if self.seconds else 0.0

    def merge_scaled(self, other: "EngineResult", times: float = 1.0) -> None:
        """Accumulate a sub-result (e.g. a while body × trip count)."""
        self.op_count += int(other.op_count * times)
        self.flops += other.flops * times
        self.mxu_flops += other.mxu_flops * times
        self.transcendentals += other.transcendentals * times
        self.hbm_bytes += other.hbm_bytes * times
        self.vmem_bytes += other.vmem_bytes * times
        self.ici_bytes += other.ici_bytes * times
        self.collective_count += int(other.collective_count * times)
        self.collective_cycles += other.collective_cycles * times
        self.exposed_collective_cycles += other.exposed_collective_cycles * times
        self.dma_cycles += other.dma_cycles * times
        self.exposed_dma_cycles += other.exposed_dma_cycles * times
        self.vmem_resident_bytes = max(
            self.vmem_resident_bytes, other.vmem_resident_bytes
        )
        self.vmem_spill_bytes += other.vmem_spill_bytes * times
        self.hbm_contention_cycles += other.hbm_contention_cycles * times
        self.orphan_async_joins += int(other.orphan_async_joins * times)
        self.unjoined_async += int(other.unjoined_async * times)
        self.unknown_trip_loops += int(other.unknown_trip_loops * times)
        self.worst_case_branches += int(other.worst_case_branches * times)
        for k, v in other.unit_busy_cycles.items():
            self.unit_busy_cycles[k] += v * times
        for k, v in other.opcode_cycles.items():
            self.opcode_cycles[k] += v * times
        for k, v in other.per_op_cycles.items():
            self.per_op_cycles[k] += v * times
        for k, v in other.per_op_count.items():
            self.per_op_count[k] += v * times
        for k, v in other.per_op_hbm_bytes.items():
            self.per_op_hbm_bytes[k] += v * times
        for k, v in other.per_op_flops.items():
            self.per_op_flops[k] += v * times
        for k, v in other.per_op_mxu_flops.items():
            self.per_op_mxu_flops[k] += v * times
        self.per_op_opcode.update(other.per_op_opcode)
        self.per_op_async.update(other.per_op_async)

    def stats_dict(self) -> dict[str, float]:
        d = {
            "sim_cycles": self.cycles,
            "sim_seconds": self.seconds,
            "op_count": self.op_count,
            "flops": self.flops,
            "mxu_flops": self.mxu_flops,
            "hbm_bytes": self.hbm_bytes,
            "vmem_bytes": self.vmem_bytes,
            "ici_bytes": self.ici_bytes,
            "collective_count": self.collective_count,
            "collective_cycles": self.collective_cycles,
            "exposed_collective_cycles": self.exposed_collective_cycles,
            "dma_cycles": self.dma_cycles,
            "exposed_dma_cycles": self.exposed_dma_cycles,
            "vmem_resident_bytes": self.vmem_resident_bytes,
            "vmem_spill_bytes": self.vmem_spill_bytes,
            "hbm_contention_cycles": self.hbm_contention_cycles,
            "orphan_async_joins": self.orphan_async_joins,
            "unjoined_async": self.unjoined_async,
            "unknown_trip_loops": self.unknown_trip_loops,
            "worst_case_branches": self.worst_case_branches,
            "mxu_utilization": self.mxu_utilization,
            "achieved_tflops": self.achieved_flops / 1e12,
            "hbm_gbps": self.hbm_gbps,
        }
        for unit, busy in self.unit_busy_cycles.items():
            d[f"busy_cycles_{unit}"] = busy
        return d


def _vmem_resident_bytes(module: ModuleTrace) -> float:
    """Total bytes XLA pinned in vmem (layout memory space ``S(1)``),
    counted once per *allocating* op.  This is the module's vmem residency
    demand; the capacity check compares it to the 128MB budget the way the
    reference checks shmem/L1 occupancy (gpu-cache.h).

    Alias chains must not be double-counted (round-4 fix: the reduction
    fixture's one 67MB carry was counted 5x — copy-start, copy-done,
    while, and the in-place body DUS all carry the same S(1) layout —
    which tripped a phantom 2.6GB spill and tripled the simulated time):

    * pass-through ops (tuple/gte/bitcast/parameter) alias — skipped,
      except entry parameters, which are real allocations;
    * ``while``/``conditional`` results alias their init/branch values;
    * ``*-done`` ops alias the buffers their ``*-start`` allocated;
    * ``copy-start`` result is (dst, src-alias, ctx) — only its largest
      leaf (the destination) is a new allocation;
    * non-entry ``dynamic-update-slice`` is the in-place carry update of
      a scan body (XLA aliases it onto the parameter)."""
    total = 0.0
    entry_name = module.entry_name
    for cname, comp in module.computations.items():
        is_entry = entry_name is not None and cname == entry_name
        for op in comp.ops:
            total += _alloc_vmem_bytes(op, is_entry)
    return total


def _alloc_vmem_bytes(op: TraceOp, is_entry: bool) -> float:
    """Vmem (``S(1)``) bytes newly allocated by one op under the alias
    rules documented on :func:`_vmem_resident_bytes`; 0 for aliases."""
    if op.opcode in FREE_OPCODES or op.base in FREE_OPCODES:
        if not (is_entry and op.opcode == "parameter"):
            return 0.0
    if op.base in ("while", "conditional", "call") or op.is_async_done:
        # while/conditional/call results alias their init/branch/callee-root
        # values — the callee's own walk already counts the allocation
        return 0.0
    if not is_entry and op.base == "dynamic-update-slice":
        return 0.0
    leaves = leaves_of(op.result)
    if op.is_async_start and op.base == "copy":
        # result is (dst, src-alias, ctx): only the leading dst
        # leaf is a new allocation (a vmem->HBM spill copy's S(1)
        # src alias must not re-count the source buffer)
        if leaves and leaves[0].memory_space != 0:
            return float(leaves[0].nbytes)
        return 0.0
    if op.is_async_start:
        # collective starts carry (operand-alias, result, ...):
        # one buffer, not the alias pair
        return float(max(
            (l.nbytes for l in leaves if l.memory_space != 0),
            default=0.0,
        ))
    return float(sum(l.nbytes for l in leaves if l.memory_space != 0))


def _vmem_peak_live_bytes(module: ModuleTrace) -> float:
    """Peak *concurrently-live* ``S(1)`` bytes — what the 128MB budget
    actually constrains.  The conservative sum (above) counts every
    allocation in the module as if simultaneous; XLA's assignment reuses
    slots across disjoint lifetimes, so a decode step whose temporaries
    *sum* to 210MB fits fine (round-4 silicon: the phantom spill priced
    its 16MB vmem slices at HBM rate, +139%).

    Per computation: parameters' vmem leaves are live throughout (they
    alias buffers carried in from the caller); local defs become live at
    their def index and die after their last use (the root lives to the
    end).  At a while/conditional/call, the callee's peak coexists with
    the caller's live set at that index — minus the carried operands,
    which the callee's parameters re-count."""
    entry_name = module.entry_name

    def comp_peak(cname: str, depth: int) -> float:
        comp = module.computations.get(cname)
        if comp is None or depth > 16:
            return 0.0
        cached = getattr(comp, "_peak_live_cache_c", None)
        if cached is not None:
            return cached
        is_entry = entry_name is not None and cname == entry_name
        n = len(comp.ops)
        last_use: dict[str, int] = {}
        for i, op in enumerate(comp.ops):
            for o in op.operands:
                last_use[o] = max(last_use.get(o, i), i)
        # extend lifetimes through aliasing consumers (gte/bitcast/tuple,
        # *-done, while/conditional/call results): the underlying buffer
        # lives until the alias's own last use.  Reverse order: an
        # alias's extended lifetime is final before its operands are
        # visited.
        ext: dict[str, int] = {}
        for i in range(n - 1, -1, -1):
            op = comp.ops[i]
            is_alias = (
                op.opcode in FREE_OPCODES or op.base in FREE_OPCODES
                or op.is_async_done
                or op.base in ("while", "conditional", "call")
                # non-entry DUS updates its source in place: the source
                # must stay live until the DUS *result*'s last use
                or (not is_entry and op.base == "dynamic-update-slice")
            )
            if not is_alias:
                continue
            eff = max(last_use.get(op.name, i), ext.get(op.name, i))
            for o in op.operands:
                ext[o] = max(ext.get(o, 0), eff)
        frees: dict[int, float] = defaultdict(float)
        live = 0.0
        local_peak = 0.0
        for i, op in enumerate(comp.ops):
            if op.base in ("while", "conditional", "call") and op.called:
                # callee temporaries coexist with everything live here;
                # subtract the carried S(1) operands the callee's params
                # re-count
                carried = sum(
                    l.nbytes
                    for o in op.operands if comp.has_op(o)
                    for l in leaves_of(comp.op(o).result)
                    if l.memory_space != 0
                )
                inner = max(
                    comp_peak(callee, depth + 1) for callee in op.called
                )
                local_peak = max(
                    local_peak, live + max(inner - carried, 0.0)
                )
            nbytes = (
                float(sum(
                    l.nbytes for l in leaves_of(op.result)
                    if l.memory_space != 0
                ))
                if op.opcode == "parameter" and not is_entry
                else _alloc_vmem_bytes(op, is_entry)
            )
            if nbytes > 0:
                live += nbytes
                if live > local_peak:
                    local_peak = live
                if op.opcode == "parameter" and not is_entry:
                    die = n  # carried state stays live for the body
                else:
                    die = max(last_use.get(op.name, n), ext.get(op.name, 0))
                frees[die] += nbytes
            live -= frees.pop(i, 0.0)
        try:
            comp._peak_live_cache_c = local_peak
        except (AttributeError, TypeError):
            pass
        return local_peak

    if entry_name is not None and entry_name in module.computations:
        return comp_peak(entry_name, 0)
    return max(
        (comp_peak(cname, 0) for cname in list(module.computations)),
        default=0.0,
    )


#: process-wide memo for the per-module derived scalars (vmem residency
#: and peak-live bytes), keyed under the module CONTENT hash: a fresh
#: parse of the same text — a serve request re-registering a trace, an
#: obs/windowed-fault replay that bypasses the result cache — skips the
#: recursive walk entirely.  Object-attr caches stay as the L0 tier.
_SCALAR_MEMO: OrderedDict = OrderedDict()
_SCALAR_MEMO_MAX = 4096
# the serving daemon prices from many request threads; the lock covers
# the LRU mutations (a move_to_end racing an eviction raises KeyError)
_SCALAR_MEMO_LOCK = threading.Lock()


def _scalar_memo_key(module: ModuleTrace, kind: str) -> tuple | None:
    h = module.meta.get("content_hash") if module.meta else None
    if not h:
        return None
    return (str(h), kind)


def _scalar_memo_get(key: tuple | None) -> float | None:
    if key is None:
        return None
    with _SCALAR_MEMO_LOCK:
        val = _SCALAR_MEMO.get(key)
        if val is not None:
            _SCALAR_MEMO.move_to_end(key)
    return val


def _scalar_memo_put(key: tuple | None, value: float) -> None:
    if key is None:
        return
    with _SCALAR_MEMO_LOCK:
        _SCALAR_MEMO[key] = value
        _SCALAR_MEMO.move_to_end(key)
        while len(_SCALAR_MEMO) > _SCALAR_MEMO_MAX:
            _SCALAR_MEMO.popitem(last=False)


def _residency_of(module: ModuleTrace) -> float:
    """Memoized vmem residency, cached ON the module (it is immutable
    after parse, and being an eq-based dataclass it is unhashable — no
    dict keying) and, when the module carries a content hash, in the
    process-wide scalar memo (repeat parses of the same text skip the
    scan).  The scan was ~30% of a small-module replay.  Lazy modules
    provide a raw-text S(1) scan so the check doesn't force a full
    parse."""
    cached = getattr(module, "_residency_cache", None)
    if cached is not None:
        return cached
    fast = getattr(module, "vmem_resident_bytes", None)
    # the raw-text scan (lazy/streaming modules) and the IR walk are
    # deliberately different approximations — memoize them as distinct
    # kinds so the value a module sees never depends on which
    # representation of the same text priced first
    kind = "resident_text" if callable(fast) else "resident_ir"
    key = _scalar_memo_key(module, kind)
    resident = _scalar_memo_get(key)
    if resident is None:
        resident = fast() if callable(fast) else _vmem_resident_bytes(module)
        _scalar_memo_put(key, resident)
    try:
        module._residency_cache = resident
    except (AttributeError, TypeError):
        pass
    return resident


class Engine:
    """Times one module on one modeled device of a topology."""

    def __init__(
        self,
        config: SimConfig,
        topology: Topology | None = None,
        cost_model: CostModel | None = None,
        record_timeline: bool = False,
        max_timeline_events: int = 100_000,
        obs=None,
        clock_scale: float = 1.0,
        hbm_scale: float = 1.0,
        pricing_backend: str | None = None,
        cancel=None,
    ):
        self.config = config
        # cooperative cancellation (tpusim.guard): a CancelToken checked
        # every CHECK_EVERY_OPS ops in the serial walk and between
        # compiled blocks in the fastpath.  None (the default) keeps the
        # hot loop at one pointer compare per stride — the healthy path
        # is arithmetically untouched either way (cancellation changes
        # WHETHER a result is produced, never its value).
        self.cancel = cancel
        self.arch = config.arch
        self.cost = cost_model or CostModel(self.arch)
        # fastpath compile results are shared process-wide only for the
        # default cost model (a caller-supplied model is outside every
        # fingerprint — its compiled columns stay pinned to the module
        # object + model token, mirroring the result-cache bypass)
        self._default_cost_model = cost_model is None
        # pricing backend (tpusim.fastpath): None/"auto" resolves to the
        # fastest available path; "serial" pins the reference walk.
        # Resolved lazily (first run) so Engine construction never pays
        # a numpy import or a dlopen.
        self.pricing_backend = pricing_backend
        self._resolved_backend: str | None = None
        self.topology = topology
        self.record_timeline = record_timeline
        self.max_timeline_events = max_timeline_events
        # instrumentation hub (tpusim.obs); the no-op default keeps the
        # hot path to one cached boolean check per op
        self.obs = obs if obs is not None else NULL_OBS
        # degraded-chip multipliers (tpusim.faults): a straggler runs its
        # core/vmem at clock_scale x nominal, a throttled HBM streams at
        # hbm_scale x nominal.  Cycles stay in NOMINAL units (the pod
        # clock), so a straggler's ops take 1/clock_scale more of them;
        # 1.0/1.0 keeps the healthy path bit-identical (no per-op branch)
        if not 0.0 < clock_scale <= 1.0 or not 0.0 < hbm_scale <= 1.0:
            raise ValueError(
                "clock_scale/hbm_scale must be in (0, 1] "
                f"(got {clock_scale}, {hbm_scale})"
            )
        self.clock_scale = float(clock_scale)
        self.hbm_scale = float(hbm_scale)
        self._degraded = clock_scale != 1.0 or hbm_scale != 1.0

    @staticmethod
    def _peak_live_of(module: ModuleTrace) -> float:
        cached = getattr(module, "_peak_live_cache", None)
        if cached is not None:
            return cached
        key = _scalar_memo_key(module, "peak_live")
        peak = _scalar_memo_get(key)
        if peak is None:
            peak = _vmem_peak_live_bytes(module)
            _scalar_memo_put(key, peak)
        try:
            module._peak_live_cache = peak
        except (AttributeError, TypeError):
            pass
        return peak

    def _topology_for(self, module: ModuleTrace) -> Topology:
        if self.topology is not None:
            return self.topology
        return torus_for(module.num_devices, self.arch.name)

    # ------------------------------------------------------------------

    def run(self, module: ModuleTrace) -> EngineResult:
        """Simulate one execution of the module's entry computation.

        Dispatches to the compiled fastpath (tpusim.fastpath) when a
        non-serial backend is available and the run carries no
        run-scoped observables; the serial walk below is the reference
        semantics both fastpath backends are byte-identical to (pinned
        by tests/test_fastpath.py and the --fastpath-parity CI smoke).
        """
        backend = self._resolved_backend
        if backend is None:
            from tpusim.fastpath.price import resolve_backend

            backend = self._resolved_backend = resolve_backend(
                self.pricing_backend
            )
        if backend != "serial":
            from tpusim.fastpath.price import fastpath_eligible, price_module

            if fastpath_eligible(self):
                return price_module(self, module, backend)
        return self._run_serial(module)

    def _run_serial(self, module: ModuleTrace) -> EngineResult:
        """The reference per-op schedule walk."""
        topo = self._topology_for(module)
        coll = make_collective_model(topo, self.arch.ici, obs=self.obs)
        result = EngineResult()
        sampler = None
        if self.obs.enabled and self.obs.sample:
            sampler = CycleWindowSampler(self.obs.window_cycles)
        spill_frac = 1.0
        if self.config.model_vmem_capacity:
            resident = _residency_of(module)
            cap = float(self.arch.vmem_bytes)
            if resident > cap > 0:
                # the conservative sum counts every allocation as
                # simultaneous; before pricing a spill, check what is
                # actually concurrently live (XLA reuses slots across
                # disjoint lifetimes — a decode step whose temporaries sum
                # to 210MB fits the 128MB budget fine).  The liveness walk
                # needs a full parse, so it only runs when the cheap bound
                # says the budget might be blown.
                resident = self._peak_live_of(module)
            result.vmem_resident_bytes = resident
            if resident > cap > 0:
                # over-subscribed vmem: only cap/resident of the pinned
                # bytes can actually live on-chip; the rest spills to HBM
                spill_frac = cap / resident
        end = self._run_computation(
            module, module.entry, t0=0.0, coll=coll, result=result, depth=0,
            spill_frac=spill_frac, sampler=sampler,
        )
        result.cycles = end
        result.seconds = self.arch.cycles_to_seconds(end)
        result.samples = sampler
        return result

    # ------------------------------------------------------------------

    def _run_computation(
        self,
        module: ModuleTrace,
        comp: Computation,
        t0: float,
        coll: CollectiveModel,
        result: EngineResult,
        depth: int,
        spill_frac: float = 1.0,
        sampler=None,
    ) -> float:
        """Walk one computation's schedule; returns the finish cycle."""
        if depth > 32:
            return t0
        a = self.arch
        # cooperative cancellation (tpusim.guard): one pointer compare
        # per op when un-governed; a real deadline/cancel check every
        # CHECK_EVERY_OPS ops.  Cancellation changes WHETHER a result is
        # produced, never its value — an armed-but-untripped token walk
        # is arithmetically identical to an unarmed one.
        cancel = self.cancel
        if cancel is not None:
            from tpusim.guard.cancel import CHECK_EVERY_OPS as _stride
        # self-profiling accumulators (tpusim.obs): wall seconds spent in
        # the cost model and ICI pricing inside this walk, reported once
        # at the end — per-op span objects would cost more than the ops
        obs = self.obs
        obs_on = obs.enabled
        cost_wall = 0.0
        cost_calls = 0
        ici_wall = 0.0
        ici_calls = 0
        if obs_on:
            from time import perf_counter as _pc
        t = t0
        ici_free = t0
        dma_free = t0
        pending: dict[str, float] = {}  # async op name -> finish cycle
        dma_names: set[str] = set()     # pending entries on the DMA channel
        # horizon until which the async DMA channel is draining HBM, plus
        # the in-flight transfer segments [start, end, bytes/cycle] — the
        # queue's remaining bytes at time t are summed from the segments
        # at each transfer's OWN rate (a relayout-derated copy queues its
        # bytes slowly; converting its horizon at pin rate would inflate
        # the fair-share penalty)
        dma_busy_until = t0
        dma_segments: list[list[float]] = []
        hbm_bpc = a.hbm_bytes_per_cycle
        dma_lat = a.seconds_to_cycles(a.dma_issue_latency)
        contend = self.config.model_hbm_contention
        overlap = self.config.overlap_collectives
        # op-granularity checkpoint/resume applies to the entry walk only
        resume_op = self.config.resume_op if depth == 0 else 0
        checkpoint_op = self.config.checkpoint_op if depth == 0 else 0
        skipped_starts: set[str] = set()

        for op_index, op in enumerate(comp.ops):
            if cancel is not None and op_index % _stride == 0:
                cancel.check()
            if checkpoint_op and op_index >= checkpoint_op:
                break
            if resume_op and op_index < resume_op:
                # fast-forward already-simulated work; remember async
                # starts so their done-ops join silently (the transfer
                # completed before the checkpoint barrier)
                if op.is_async_start:
                    skipped_starts.add(op.name)
                continue
            base = op.base

            # ---- control flow: recurse ---------------------------------
            if base == "while" and len(op.called) >= 1:
                body_name = op.attrs.get("body", "").lstrip("%") or op.called[0]
                trips = while_trip_count(op, 0)
                if trips <= 0:  # no backend_config: infer from the IV pattern
                    from tpusim.trace.loop_analysis import infer_trip_count

                    trips = infer_trip_count(module, comp, op, -1)
                    if trips < 0:
                        trips = self.config.default_loop_trip_count
                        result.unknown_trip_loops += 1
                sub = EngineResult()
                sub_sampler = (
                    _sub_sampler_like(sampler) if sampler is not None
                    else None
                )
                body_end = self._run_computation(
                    module, module.computation(body_name), 0.0, coll, sub,
                    depth + 1, spill_frac, sampler=sub_sampler,
                )
                result.merge_scaled(sub, float(trips))
                dur = body_end * trips + a.op_overhead_cycles * (trips + 1)
                if sub_sampler is not None and body_end > 0:
                    # the timeline records one opaque while event; the
                    # sampler sees through it — one body copy per trip,
                    # clamped to the body's true duration and spaced by
                    # the same per-trip overhead the duration carries
                    # (otherwise late trips drift earlier than the
                    # timeline by overhead*(k+1) cycles)
                    sampler.add_series(
                        sub_sampler,
                        offset=t + a.op_overhead_cycles,
                        repeats=int(trips),
                        period=body_end + a.op_overhead_cycles,
                        length=body_end,
                    )
                self._emit(result, op, t, t + dur, Unit.SCALAR)
                t += dur
                result.op_count += 1
                continue
            if base == "conditional" and op.called:
                durs = []
                subs = []
                sub_samplers = []
                for branch in op.called:
                    if branch not in module.computations:
                        continue
                    sub = EngineResult()
                    ss = (
                        _sub_sampler_like(sampler) if sampler is not None
                        else None
                    )
                    d = self._run_computation(
                        module, module.computation(branch), 0.0, coll, sub,
                        depth + 1, spill_frac, sampler=ss,
                    )
                    durs.append(d)
                    subs.append(sub)
                    sub_samplers.append(ss)
                if durs:
                    worst = max(range(len(durs)), key=lambda i: durs[i])
                    result.merge_scaled(subs[worst], 1.0)
                    if sub_samplers[worst] is not None:
                        sampler.add_series(
                            sub_samplers[worst], offset=t,
                            length=durs[worst],
                        )
                    dur = durs[worst] + a.op_overhead_cycles
                    if len(durs) > 1 and max(durs) > 1.5 * min(durs):
                        # the worst-case assumption is materially wrong for
                        # whichever arm actually runs — surface it, like
                        # unknown_trip_loops does for loop bounds
                        result.worst_case_branches += 1
                    self._emit(result, op, t, t + dur, Unit.SCALAR)
                    t += dur
                result.op_count += 1
                continue
            if base == "call" and op.called:
                sub = EngineResult()
                sub_sampler = (
                    _sub_sampler_like(sampler) if sampler is not None
                    else None
                )
                d = self._run_computation(
                    module, module.computation(op.called[0]), 0.0, coll, sub,
                    depth + 1, spill_frac, sampler=sub_sampler,
                )
                result.merge_scaled(sub, 1.0)
                if sub_sampler is not None:
                    sampler.add_series(sub_sampler, offset=t, length=d)
                self._emit(result, op, t, t + d, Unit.SCALAR)
                t += d
                result.op_count += 1
                continue

            # ---- async joins -------------------------------------------
            if op.is_async_done:
                src = op.operands[0] if op.operands else None
                if src in skipped_starts:
                    # started before the resume point: complete by now
                    result.op_count += 1
                    continue
                if src not in pending:
                    result.orphan_async_joins += 1
                finish = pending.pop(src, t)
                waited = max(0.0, finish - t)
                if op.base in ("all-reduce", "all-gather", "reduce-scatter",
                               "all-to-all", "collective-permute",
                               "collective-broadcast", "ragged-all-to-all"):
                    result.exposed_collective_cycles += waited
                else:
                    result.exposed_dma_cycles += waited
                t = max(t, finish)
                result.op_count += 1
                continue

            if obs_on:
                _t = _pc()
                cost = self.cost.op_cost(op, comp, module)
                cost_wall += _pc() - _t
                cost_calls += 1
            else:
                cost = self.cost.op_cost(op, comp, module)

            # ---- degraded chip (tpusim.faults): straggler/HBM throttle -
            # (free ops — parameter/tuple/bitcast — cost 0 and stay 0:
            # there is no work to slow down)
            if self._degraded and cost.cycles > 0:
                cs, hs = self.clock_scale, self.hbm_scale
                # core + vmem run on the chip clock; HBM is derated
                # independently.  Cycles are nominal, so slower silicon
                # means MORE nominal cycles; the max() keeps floors
                # (dispatch, small-kernel) monotone under degradation.
                cost.compute_cycles /= cs
                cost.hbm_rate_scale *= hs
                cost.vmem_rate_scale *= cs
                cost.mem_cycles = max(
                    cost.hbm_bytes / (hbm_bpc * cost.hbm_rate_scale),
                    cost.vmem_bytes
                    / (a.vmem_bytes_per_cycle * cost.vmem_rate_scale),
                )
                cost.cycles = max(
                    cost.cycles,
                    a.op_overhead_cycles / cs + max(
                        cost.compute_cycles, cost.mem_cycles
                    ),
                )

            # ---- vmem capacity: spill the over-subscribed fraction -----
            if spill_frac < 1.0 and cost.vmem_bytes > 0:
                spilled = cost.vmem_bytes * (1.0 - spill_frac)
                cost.vmem_bytes -= spilled
                cost.hbm_bytes += spilled
                result.vmem_spill_bytes += spilled
                cost.mem_cycles = max(
                    cost.hbm_bytes / (hbm_bpc * cost.hbm_rate_scale),
                    cost.vmem_bytes
                    / (a.vmem_bytes_per_cycle * cost.vmem_rate_scale),
                )
                # spilling only adds traffic: never below the original
                # price (which may carry the small-kernel dispatch floor)
                cost.cycles = max(
                    cost.cycles,
                    a.op_overhead_cycles + max(
                        cost.compute_cycles, cost.mem_cycles
                    ),
                )

            # ---- collectives -------------------------------------------
            if op.is_collective:
                if obs_on:
                    _t = _pc()
                    seconds = coll.seconds(op.collective, cost.ici_bytes)
                    ici_wall += _pc() - _t
                    ici_calls += 1
                else:
                    seconds = coll.seconds(op.collective, cost.ici_bytes)
                dur = a.seconds_to_cycles(seconds)
                result.collective_count += 1
                result.ici_bytes += cost.ici_bytes
                result.collective_cycles += dur
                result.unit_busy_cycles[Unit.ICI.value] += dur
                result.opcode_cycles[base] += dur
                if op.is_async_start and overlap:
                    start = max(t, ici_free)
                    pending[op.name] = start + dur
                    ici_free = start + dur
                    if sampler is not None:
                        sampler.add("ici", start, start + dur,
                                    ici_bytes=cost.ici_bytes)
                    self._emit(result, op, start, start + dur, Unit.ICI)
                    t += a.op_overhead_cycles  # issue cost on the core
                else:
                    start = max(t, ici_free)
                    if sampler is not None:
                        sampler.add("ici", start, start + dur,
                                    ici_bytes=cost.ici_bytes)
                    self._emit(result, op, start, start + dur, Unit.ICI)
                    t = start + dur
                    ici_free = t
                    result.exposed_collective_cycles += dur
                    if op.is_async_start:
                        # already complete when the done-op arrives; register
                        # so the join doesn't count as orphaned
                        pending[op.name] = t
                result.op_count += 1
                continue

            # ---- async DMA (copy-start etc.) ---------------------------
            if op.is_async_start:
                dur = cost.cycles
                start = max(t, dma_free)
                # issue latency (descriptor setup + first byte) delays the
                # completion but does not occupy the channel: TPUs run many
                # DMA engines, so back-to-back small transfers pipeline
                # their latencies (lstm fixture: 8KB loop copies at 1.57us
                # each, pure latency) while payloads serialize on bandwidth
                pending[op.name] = start + dma_lat + dur
                dma_names.add(op.name)
                dma_free = start + dur
                if cost.hbm_bytes > 0:
                    dma_busy_until = max(dma_busy_until, start + dur)
                    if dur > 0:
                        dma_segments.append(
                            [start, start + dur, cost.hbm_bytes / dur]
                        )
                result.dma_cycles += dur
                result.unit_busy_cycles[Unit.DMA.value] += dur
                result.opcode_cycles[base] += dur
                result.hbm_bytes += cost.hbm_bytes
                result.per_op_hbm_bytes[op.name] += cost.hbm_bytes
                if sampler is not None:
                    sampler.add("dma", start, start + dur,
                                hbm_bytes=cost.hbm_bytes,
                                vmem_bytes=cost.vmem_bytes)
                # per-op correlation sees the EXPOSURE (queueing +
                # latency + transfer — the device's async events span
                # issue to completion); the timeline keeps the channel
                # occupancy span
                self._emit(
                    result, op, start, start + dur, Unit.DMA,
                    per_op_span=(t, start + dma_lat + dur),
                )
                t += a.op_overhead_cycles
                result.op_count += 1
                continue

            # ---- ordinary synchronous op -------------------------------
            dur = cost.cycles
            if contend and cost.hbm_bytes > 0 and dma_busy_until > t:
                # the async DMA queue and this op stream HBM concurrently;
                # fair-share split: while both are active each gets half
                # the bandwidth, so each side pays the overlapped bytes
                # once more (the FR-FCFS-scheduler slot, dram_sched.h:41)
                dma_segments = [s for s in dma_segments if s[1] > t]
                q_bytes = sum(
                    s[2] * (s[1] - max(t, s[0])) for s in dma_segments
                )
                shared = min(cost.hbm_bytes, q_bytes)
                penalty = shared / hbm_bpc
                hbm_time = (
                    cost.hbm_bytes / (hbm_bpc * cost.hbm_rate_scale)
                    + penalty
                )
                mem_cycles = max(
                    hbm_time,
                    cost.vmem_bytes
                    / (a.vmem_bytes_per_cycle * cost.vmem_rate_scale),
                )
                # contention only slows an op down: never below the
                # uncontended price (which may carry the dispatch floor)
                new_dur = max(dur, a.op_overhead_cycles + max(
                    cost.compute_cycles, mem_cycles
                ))
                result.hbm_contention_cycles += (
                    max(new_dur - dur, 0.0) + penalty
                )
                # the DMA side loses the same bandwidth: stretch its
                # in-flight finishes and the channel horizon
                for name in dma_names:
                    fin = pending.get(name)
                    if fin is not None and fin > t:
                        pending[name] = fin + penalty
                dma_free += penalty
                dma_busy_until += penalty
                for s in dma_segments:
                    # the in-flight transfers are delayed by the same
                    # bandwidth loss their queue inflicted on this op;
                    # an already-started segment keeps its remaining
                    # bytes and drains them over the stretched window
                    if s[0] >= t:
                        s[0] += penalty
                        s[1] += penalty
                    else:
                        remaining = s[2] * (s[1] - t)
                        s[0] = t
                        s[1] += penalty
                        if s[1] > t:
                            s[2] = remaining / (s[1] - t)
                dur = new_dur
            if dur > 0:
                self._emit(result, op, t, t + dur, cost.unit)
                if sampler is not None:
                    sampler.add(
                        cost.unit.value, t, t + dur,
                        hbm_bytes=cost.hbm_bytes,
                        vmem_bytes=cost.vmem_bytes,
                        flops=cost.flops,
                        mxu_flops=cost.mxu_flops,
                        transcendentals=cost.transcendentals,
                    )
            t += dur
            result.op_count += 1
            result.flops += cost.flops
            result.mxu_flops += cost.mxu_flops
            result.transcendentals += cost.transcendentals
            result.hbm_bytes += cost.hbm_bytes
            result.vmem_bytes += cost.vmem_bytes
            if cost.hbm_bytes > 0:
                result.per_op_hbm_bytes[op.name] += cost.hbm_bytes
            if cost.flops > 0:
                result.per_op_flops[op.name] += cost.flops
            if cost.mxu_flops > 0:
                result.per_op_mxu_flops[op.name] += cost.mxu_flops
            if dur > 0:
                result.unit_busy_cycles[cost.unit.value] += dur
                result.opcode_cycles[base] += dur

        # drain: the program isn't done until pending transfers complete;
        # leftovers indicate a truncated/corrupt trace (async-start with no
        # join) — surfaced like the reference's deadlock check.  At an
        # op-granularity checkpoint the drain is the barrier itself: the
        # in-flight transfers are legitimate (their done-ops are in the
        # resume half), not trace corruption.
        stopped_at_checkpoint = (
            checkpoint_op and len(comp.ops) > checkpoint_op
        )
        if not stopped_at_checkpoint:
            result.unjoined_async += len(pending)
        for finish in pending.values():
            t = max(t, finish)
        if obs_on:
            if cost_calls:
                obs.add_time("cost", cost_wall, cost_calls)
            if ici_calls:
                obs.add_time("ici", ici_wall, ici_calls)
        return t

    # ------------------------------------------------------------------

    def _emit(
        self, result: EngineResult, op: TraceOp, start: float, end: float,
        unit: Unit,
        per_op_span: tuple[float, float] | None = None,
    ) -> None:
        # per-instruction aggregates are always recorded (cheap dict adds;
        # per-op correlation needs them even without the full timeline).
        # ``per_op_span`` lets async transfers report their EXPOSURE
        # (issue->completion) to correlation while the timeline keeps the
        # channel-occupancy span — two consumers, two observables.
        po_start, po_end = per_op_span if per_op_span else (start, end)
        result.per_op_cycles[op.name] += po_end - po_start
        result.per_op_count[op.name] += 1.0
        result.per_op_opcode.setdefault(op.name, op.base)
        if op.is_async_start:
            result.per_op_async[op.name] = True
        if not self.record_timeline:
            return
        if len(result.timeline) >= self.max_timeline_events:
            return
        result.timeline.append(
            TimelineEvent(op.name, op.opcode, unit.value, start, end)
        )
