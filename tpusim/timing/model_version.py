"""Content hash of the timing model.

Committed correlation artifacts (``reports/correl_ops.json``) must be
regenerated whenever the model that produced them changes — round 4
shipped a stale artifact that described a model two commits gone
(VERDICT r4 Weak #1).  The fix is mechanical: every artifact is stamped
with a hash of the model-defining sources, and a fast-tier test compares
the stamp against the current tree.  The reference gets the same
guarantee socially (correlation republished every CI run,
``Jenkinsfile:83-97``); a hash makes it a gate instead of a habit.
"""

from __future__ import annotations

import hashlib
from pathlib import Path

__all__ = ["MODEL_FILES", "model_version"]

_REPO = Path(__file__).resolve().parents[2]

#: the files whose content defines the timing model's predictions: the
#: cost model, the schedule-walking engine, the config/arch presets, the
#: ICI models, and the committed tuned overlay that load_config applies
#: by default.  Paths are repo-relative.
MODEL_FILES: tuple[str, ...] = (
    "tpusim/timing/cost.py",
    "tpusim/timing/engine.py",
    "tpusim/timing/config.py",
    "tpusim/timing/arch.py",
    "tpusim/ici/collectives.py",
    "tpusim/ici/detailed.py",
    "tpusim/ici/topology.py",
    "configs/v5e.tuned.flags",
)


#: per-root digest memo — the sources cannot change under a running
#: process, and the serving daemon computes a version per request
#: (every per-request cache view stamps one); eight file reads per
#: request is measurable, one per process is free
_version_cache: dict[str, str] = {}


def model_version(repo_root: str | Path | None = None) -> str:
    """Short, stable digest of the current timing model's sources
    (computed once per process per root).

    Missing files hash as empty (a deleted overlay still changes the
    digest relative to a tree that had one)."""
    root = Path(repo_root) if repo_root is not None else _REPO
    key = str(root)
    cached = _version_cache.get(key)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for rel in MODEL_FILES:
        p = root / rel
        h.update(rel.encode())
        h.update(b"\0")
        h.update(p.read_bytes() if p.is_file() else b"")
        h.update(b"\0")
    return _version_cache.setdefault(key, h.hexdigest()[:16])
