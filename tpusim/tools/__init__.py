"""Standalone analysis tools — the ``util/tracer_nvbit/others/`` parity
slot: the reference ships auxiliary NVBit tools alongside its tracer
(bbv_tool for SimPoint basic-block vectors, occupancy_calc_tool,
silicon_checkpoint_tool); tpusim ships the HLO-level equivalents
(:mod:`tpusim.tools.bbv`, :mod:`tpusim.tools.occupancy`, and buffer
snapshots in :mod:`tpusim.tracer.capture`)."""
