"""Op-class interval vectors for SimPoint-style phase sampling.

The rebuild of the reference's bbv_tool (``util/tracer_nvbit/others/
bbv_tool/bbv_count.cu:56-104``): there, per-warp basic-block execution
counts per instruction interval feed SimPoint to pick representative
simulation regions.  At HLO granularity the analogue is per-interval
opcode-class frequency vectors over a module's flattened op schedule —
long training programs (scan loops unrolled by trip count) get phase
vectors SimPoint can cluster, so one representative window per phase can
be simulated instead of the whole program.

Output format matches SimPoint's frequency-vector input: one line per
interval, ``T:dim:count`` pairs (dims are 1-based, stable across a run).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator

from tpusim.ir import Computation, ModuleTrace, TraceOp
from tpusim.timing.cost import while_trip_count

__all__ = ["BBVResult", "compute_bbv", "write_simpoint_bb"]


@dataclass
class BBVResult:
    interval_ops: int
    #: opcode -> stable 1-based dimension id
    dims: dict[str, int] = field(default_factory=dict)
    #: one vector per interval: {dim_id: count}
    vectors: list[dict[int, int]] = field(default_factory=list)

    @property
    def num_intervals(self) -> int:
        return len(self.vectors)


def _walk_schedule(
    module: ModuleTrace, comp: Computation, default_trips: int,
    depth: int = 0,
) -> Iterator[TraceOp]:
    """Flatten the schedule the way the engine executes it: while bodies
    repeat trip-count times (same resolution chain as the engine:
    backend_config, then induction-variable inference, then the default),
    fusions/calls recurse."""
    if depth > 32:
        return
    for op in comp.ops:
        base = op.base
        if base == "while" and op.called:
            body_name = op.attrs.get("body", "").lstrip("%") or op.called[0]
            trips = while_trip_count(op, 0)
            if trips <= 0:
                from tpusim.trace.loop_analysis import infer_trip_count

                trips = infer_trip_count(module, comp, op, -1)
                if trips < 0:
                    trips = default_trips
            body = module.computation(body_name)
            for _ in range(max(trips, 1)):
                yield from _walk_schedule(
                    module, body, default_trips, depth + 1
                )
            continue
        if base in ("fusion", "call") and op.called:
            yield from _walk_schedule(
                module, module.computation(op.called[0]), default_trips,
                depth + 1,
            )
            continue
        yield op


def compute_bbv(
    module: ModuleTrace, interval_ops: int = 1000, default_trips: int = 1
) -> BBVResult:
    """Opcode-frequency vector per ``interval_ops``-op window of the
    flattened execution schedule."""
    if interval_ops <= 0:
        raise ValueError("interval_ops must be positive")
    res = BBVResult(interval_ops=interval_ops)
    cur: dict[int, int] = {}
    n = 0
    for op in _walk_schedule(module, module.entry, default_trips):
        dim = res.dims.setdefault(op.base, len(res.dims) + 1)
        cur[dim] = cur.get(dim, 0) + 1
        n += 1
        if n >= interval_ops:
            res.vectors.append(cur)
            cur, n = {}, 0
    if cur:
        res.vectors.append(cur)
    return res


def write_simpoint_bb(res: BBVResult, path: str | Path) -> None:
    """SimPoint frequency-vector file: ``T:dim:count :dim:count ...``."""
    with open(path, "w") as f:
        for vec in res.vectors:
            parts = [f":{dim}:{count}" for dim, count in sorted(vec.items())]
            f.write("T" + " ".join(parts) + "\n")
