"""MXU / vmem occupancy calculator.

The rebuild of the reference's occupancy_calc_tool (``util/tracer_nvbit/
others/occupancy_calc_tool/``): there, an NVBit tool reports achievable SM
occupancy from register/shared-mem/block limits.  The TPU questions are
different but isomorphic: for each matmul-shaped op, how much of the
128x128 systolic array do the shapes actually cover (padding waste on the
K/N tile grid and the 8-row M granularity), and does the working set fit
vmem?  The report flags the ops whose shapes starve the MXU — the
first thing to look at when ``mxu_utilization`` is low.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from tpusim.ir import Computation, ModuleTrace, TraceOp
from tpusim.timing.config import ArchConfig
from tpusim.timing.cost import conv_dims, dot_dims

__all__ = ["OpOccupancy", "OccupancyReport", "occupancy_report"]


@dataclass
class OpOccupancy:
    name: str
    opcode: str
    b: int
    m: int
    n: int
    k: int
    dtype: str
    #: fraction of the K x N tile grid the shapes fill (padding waste)
    tile_fill: float
    #: fraction of streamed rows that are real (M vs 8-row granularity)
    row_fill: float
    #: fill/drain overhead share of a pass (small-M penalty)
    pipeline_eff: float
    #: operand+result bytes vs vmem capacity
    vmem_fraction: float

    @property
    def mxu_occupancy(self) -> float:
        return self.tile_fill * self.row_fill * self.pipeline_eff


@dataclass
class OccupancyReport:
    arch: str
    ops: list[OpOccupancy] = field(default_factory=list)

    @property
    def worst(self) -> list[OpOccupancy]:
        return sorted(self.ops, key=lambda o: o.mxu_occupancy)

    def summary_lines(self, limit: int = 10) -> list[str]:
        out = [
            f"occupancy report ({self.arch}): {len(self.ops)} "
            f"matmul-shaped ops"
        ]
        if not self.ops:
            return out
        mean = sum(o.mxu_occupancy for o in self.ops) / len(self.ops)
        out.append(f"mean MXU occupancy = {mean:.1%}")
        out.append(
            f"{'op':32s} {'BxMxNxK':>20s} {'tile':>6s} {'rows':>6s} "
            f"{'pipe':>6s} {'occ':>6s} {'vmem':>6s}"
        )
        for o in self.worst[:limit]:
            dims = f"{o.b}x{o.m}x{o.n}x{o.k}"
            out.append(
                f"{o.name[:32]:32s} {dims:>20s} {o.tile_fill:6.1%} "
                f"{o.row_fill:6.1%} {o.pipeline_eff:6.1%} "
                f"{o.mxu_occupancy:6.1%} {o.vmem_fraction:6.1%}"
            )
        return out


def _op_bytes(comp: Computation, op: TraceOp) -> float:
    from tpusim.ir import leaves_of

    total = sum(leaf.nbytes for leaf in leaves_of(op.result))
    for operand in op.operands:
        if comp.has_op(operand):
            total += sum(
                leaf.nbytes for leaf in leaves_of(comp.op(operand).result)
            )
    return float(total)


def _occupancy_for(
    arch: ArchConfig, comp: Computation, op: TraceOp,
    b: int, m: int, n: int, k: int, dtype: str,
) -> OpOccupancy:
    rows, cols = arch.mxu_rows, arch.mxu_cols
    k_tiles = max(math.ceil(k / rows), 1)
    n_tiles = max(math.ceil(n / cols), 1)
    tile_fill = (k * n) / (k_tiles * rows * n_tiles * cols)
    m_pad = max(8, math.ceil(m / 8) * 8)
    row_fill = m / m_pad
    # mirror CostModel.mxu_cycles: per-pass cost floors at the weight-load
    # stall (double-buffered tiles), and fill/drain is paid once per op
    passes = b * k_tiles * n_tiles
    serial = max(math.ceil(passes / arch.mxu_count), 1)
    per_pass = max(m_pad, arch.mxu_weight_stall_cycles)
    pipeline_eff = (serial * m_pad) / (
        serial * per_pass + arch.mxu_fill_cycles
    )
    vmem_fraction = _op_bytes(comp, op) / max(arch.vmem_bytes, 1)
    return OpOccupancy(
        name=op.name, opcode=op.base, b=b, m=m, n=n, k=k, dtype=dtype,
        tile_fill=tile_fill, row_fill=row_fill, pipeline_eff=pipeline_eff,
        vmem_fraction=vmem_fraction,
    )


def occupancy_report(
    module: ModuleTrace, arch: ArchConfig
) -> OccupancyReport:
    """Scan every computation for matmul-shaped ops (dot / convolution)
    and compute their array occupancy."""
    report = OccupancyReport(arch=arch.name)
    for comp in module.computations.values():
        for op in comp.ops:
            base = op.base
            try:
                if base == "dot":
                    b, m, n, k, dtype = dot_dims(op, comp)
                elif base == "convolution":
                    b, m, n, k, dtype = conv_dims(op, comp)
                else:
                    continue
            except (IndexError, KeyError, ValueError):
                continue
            report.ops.append(
                _occupancy_for(arch, comp, op, b, m, n, k, dtype)
            )
    return report
