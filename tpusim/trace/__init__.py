"""Stored-trace frontend: HLO text parsing + on-disk trace format.

Mirror of the reference's standalone ``gpu-simulator/trace-parser/`` (it is
dependency-free and reusable; ours likewise depends only on :mod:`tpusim.ir`).
"""

from tpusim.trace.hlo_text import parse_hlo_module, parse_shape
from tpusim.trace.format import (
    TraceDir,
    load_trace,
    save_trace,
    parse_commandlist,
)

__all__ = [
    "parse_hlo_module",
    "parse_shape",
    "TraceDir",
    "load_trace",
    "save_trace",
    "parse_commandlist",
]
