"""On-disk trace format — the rebuild of the reference's trace directory
(``kernel-N.traceg`` + ``kernelslist.g`` + ``stats.csv``, produced by
``util/tracer_nvbit/tracer_tool/tracer_tool.cu:447-483`` and
``traces-processing/post-traces-processing.cpp``).

Layout of a trace directory::

    <dir>/
      meta.json                  capture metadata (device kind, topology, ...)
      modules/<name>.hlo         scheduled optimized HLO text (one per module)
      commandlist.jsonl          per-device program streams (kernelslist.g)

The command list is JSONL — structured, greppable, and versioned — instead of
the reference's positional text lines; ``nccl*`` command passthrough
(``post-traces-processing.cpp:72-73``) becomes first-class ``collective``
records that carry byte counts and replica groups (fixing the reference's
recorded-nothing gap, SURVEY.md §5).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from pathlib import Path

from tpusim.ir import (
    CollectiveInfo,
    CommandKind,
    PodTrace,
    TraceCommand,
)

__all__ = [
    "TraceDir",
    "save_trace",
    "load_trace",
    "parse_commandlist",
    "iter_commandlist",
]

TRACE_FORMAT_VERSION = 1


@dataclass
class TraceDir:
    """Handle to a trace directory on disk."""

    path: Path
    meta: dict = field(default_factory=dict)

    @property
    def modules_dir(self) -> Path:
        return self.path / "modules"

    @property
    def commandlist_path(self) -> Path:
        return self.path / "commandlist.jsonl"

    def module_names(self) -> list[str]:
        if not self.modules_dir.is_dir():
            return []
        names = {p.stem for p in self.modules_dir.glob("*.hlo")}
        names.update(
            p.name[: -len(".hlo.gz")]
            for p in self.modules_dir.glob("*.hlo.gz")
        )
        return sorted(names)


# ---------------------------------------------------------------------------
# Command (de)serialization
# ---------------------------------------------------------------------------


def _collective_to_json(c: CollectiveInfo | None) -> dict | None:
    if c is None:
        return None
    return {
        "kind": c.kind,
        "replica_groups": [list(g) for g in c.replica_groups],
        "channel_id": c.channel_id,
        "use_global_device_ids": c.use_global_device_ids,
        "source_target_pairs": [list(p) for p in c.source_target_pairs],
        "split_dimension": c.split_dimension,
        "dimensions": list(c.dimensions),
    }


def _collective_from_json(d: dict | None) -> CollectiveInfo | None:
    if d is None:
        return None
    return CollectiveInfo(
        kind=d["kind"],
        replica_groups=tuple(tuple(g) for g in d.get("replica_groups", [])),
        channel_id=d.get("channel_id"),
        use_global_device_ids=d.get("use_global_device_ids", False),
        source_target_pairs=tuple(
            (p[0], p[1]) for p in d.get("source_target_pairs", [])
        ),
        split_dimension=d.get("split_dimension"),
        dimensions=tuple(d.get("dimensions", [])),
    )


def command_to_json(cmd: TraceCommand) -> dict:
    return {
        "kind": cmd.kind.value,
        "stream": cmd.stream_id,
        "device": cmd.device_id,
        "bytes": cmd.nbytes,
        "module": cmd.module,
        "collective": _collective_to_json(cmd.collective),
        "attrs": cmd.attrs,
    }


def command_from_json(d: dict) -> TraceCommand:
    return TraceCommand(
        kind=CommandKind(d["kind"]),
        stream_id=d.get("stream", 0),
        device_id=d.get("device", 0),
        nbytes=d.get("bytes", 0),
        module=d.get("module"),
        collective=_collective_from_json(d.get("collective")),
        attrs=d.get("attrs", {}),
    )


def iter_commandlist(path: str | Path):
    """Yield ``(lineno, record_dict | None, error | None)`` per non-blank
    ``commandlist.jsonl`` line (1-based line numbers).

    The shared substrate of :func:`parse_commandlist` and the static
    analyzer (``tpusim.analysis.trace_passes``): the loader wants the
    records, the linter wants the *line anchors* and the per-line parse
    errors — one walk serves both so they can never disagree about which
    line a record came from."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                yield lineno, None, f"invalid JSON: {e}"
                continue
            if not isinstance(rec, dict):
                yield lineno, None, f"record is not an object: {rec!r}"
                continue
            yield lineno, rec, None


def parse_commandlist(path: str | Path) -> list[TraceCommand]:
    """Parse a ``commandlist.jsonl`` — the ``parse_commandlist_file``
    equivalent (``trace_parser.cc:220``)."""
    cmds = []
    for lineno, rec, err in iter_commandlist(path):
        if err is not None:
            raise ValueError(f"{path}:{lineno}: {err}")
        cmds.append(command_from_json(rec))
    return cmds


# ---------------------------------------------------------------------------
# Save / load full pod traces
# ---------------------------------------------------------------------------


#: modules at or above this text size are stored gzipped (compress="auto")
COMPRESS_THRESHOLD_BYTES = 1 * 1024 * 1024


def save_trace(
    path: str | Path,
    modules: dict[str, str],
    commands: list[TraceCommand],
    meta: dict | None = None,
    compress: bool | str = "auto",
) -> TraceDir:
    """Write a trace directory.  ``modules`` maps module name → HLO text.

    ``compress``: True = always gzip module text, False = never, "auto" =
    gzip modules above :data:`COMPRESS_THRESHOLD_BYTES` (optimized HLO for
    large models is 100s of MB and compresses ~10x — the
    ``trace_parser.cc:86-125`` xz-pipe equivalent)."""
    import gzip

    path = Path(path)
    (path / "modules").mkdir(parents=True, exist_ok=True)
    meta = dict(meta or {})
    meta.setdefault("format_version", TRACE_FORMAT_VERSION)
    with open(path / "meta.json", "w") as f:
        json.dump(meta, f, indent=2, default=str)
    for name, text in modules.items():
        safe = name.replace(os.sep, "_")
        gz = compress is True or (
            compress == "auto" and len(text) >= COMPRESS_THRESHOLD_BYTES
        )
        if gz:
            with gzip.open(
                path / "modules" / f"{safe}.hlo.gz", "wt",
                compresslevel=6,
            ) as f:
                f.write(text)
        else:
            with open(path / "modules" / f"{safe}.hlo", "w") as f:
                f.write(text)
    with open(path / "commandlist.jsonl", "w") as f:
        for cmd in commands:
            f.write(json.dumps(command_to_json(cmd)) + "\n")
    return TraceDir(path=path, meta=meta)


def select_module(pod: "PodTrace", want: str | None):
    """The ONE policy for resolving a manifest entry to a module: the
    named module when ``want`` is given, the sole module otherwise, and a
    hard error on ambiguity.  Shared by bench replay, the refiner, and
    correlation so they can never silently disagree about which program
    a fixture measures."""
    if want is not None:
        return pod.modules[want]
    if len(pod.modules) == 1:
        return next(iter(pod.modules.values()))
    raise ValueError(
        f"trace has {len(pod.modules)} modules "
        f"({sorted(pod.modules)}); manifest entry must name one via "
        f"'module'"
    )


def load_trace(
    path: str | Path, lenient: bool = False,
    defer_parse: bool | None = None,
) -> PodTrace:
    """Load a trace directory into a :class:`PodTrace` (modules parsed).

    ``lenient=True`` parses module text in salvage mode (malformed lines
    skipped with a counted warning — the ``--lenient-parse`` flag);
    strict parsing, which raises on the first corrupt line, stays the
    default.  Lenient mode always parses eagerly in Python: per-line
    recovery needs the reference parser, not the native scanner or the
    lazy span index.

    ``defer_parse=True`` builds every in-memory module lazily regardless
    of size (computations parse on first IR access).  The default
    (``None``) defers exactly when a durable compile store is active
    (:func:`tpusim.fastpath.store.compile_store_active`): with a warm
    store, pricing runs entirely from mmapped compiled columns and the
    deferred parse never happens — the cold-path contract.  The lazy
    module stamps the same ``content_hash`` the eager path does, so
    every cache key is identical either way."""
    path = Path(path)
    # one directory read answers every existence question (a trace load
    # under the durable compile tier is first-touch latency — per-file
    # stat probes were a measurable slice of it)
    try:
        with os.scandir(path) as it:
            root_names = {de.name for de in it}
    except (FileNotFoundError, NotADirectoryError):
        raise FileNotFoundError(
            f"trace directory not found: {path}"
        ) from None
    if "modules" not in root_names and \
            "commandlist.jsonl" not in root_names:
        raise FileNotFoundError(
            f"{path} is not a trace directory (no modules/ or "
            f"commandlist.jsonl)"
        )
    meta: dict = {}
    if "meta.json" in root_names:
        with open(path / "meta.json") as f:
            meta = json.load(f)

    from tpusim.trace.lazy import (
        LAZY_THRESHOLD_BYTES,
        STREAM_THRESHOLD_BYTES,
        parse_hlo_module_lazy,
        parse_hlo_module_streaming,
    )
    from tpusim.trace.native import parse_hlo_module_fast

    stream_threshold = int(os.environ.get(
        "TPUSIM_STREAM_THRESHOLD", STREAM_THRESHOLD_BYTES
    ))
    if defer_parse is None and not lenient:
        from tpusim.fastpath.store import compile_store_active

        defer_parse = compile_store_active()

    pod = PodTrace(meta=meta)
    modules_dir = path / "modules"
    # one scandir pass instead of two sorted globs + a stat per module:
    # DirEntry.stat() rides the directory read, and trace loading is
    # the first-touch path the durable compile tier exists to shorten
    plain: list[tuple[str, str, int]] = []
    gzipped: list[tuple[str, str]] = []
    try:
        with os.scandir(modules_dir) as it:
            for de in it:
                n = de.name
                if n.endswith(".hlo"):
                    plain.append((n[:-4], de.path, de.stat().st_size))
                elif n.endswith(".hlo.gz"):
                    gzipped.append((n[: -len(".hlo.gz")], de.path))
    except (FileNotFoundError, NotADirectoryError):
        pass
    if plain or gzipped:
        import gzip

        # str entries are in-memory module text; Path entries are
        # file-backed modules above the streaming threshold (priced
        # computation-by-computation with bounded RSS — the text is
        # never read whole).  Lenient salvage and gzipped modules stay
        # in memory: per-line recovery and decompression both need the
        # full text anyway.
        entries: list[tuple[str, str | Path]] = []
        for key, fp, size in sorted(plain):
            if not lenient and size >= stream_threshold:
                entries.append((key, Path(fp)))
            else:
                with open(fp) as f:
                    entries.append((key, f.read()))
        for key, fp in sorted(gzipped):
            with gzip.open(fp, "rt") as f:
                entries.append((key, f.read()))
        for key, src in entries:
            # large modules parse lazily: the engine only materializes the
            # computations its schedule walk actually reaches
            if isinstance(src, Path):
                # the streaming index pass computes the content hash
                # (chunked) itself
                mod = parse_hlo_module_streaming(src, name_hint=key)
            elif lenient:
                from tpusim.trace.hlo_text import parse_hlo_module

                mod = parse_hlo_module(src, name_hint=key, strict=False)
            elif defer_parse or len(src) >= LAZY_THRESHOLD_BYTES:
                mod = parse_hlo_module_lazy(src, name_hint=key)
            else:
                mod = parse_hlo_module_fast(src, name_hint=key)
            # file name is the trace key; HloModule header name may differ
            pod.modules[key] = mod
            mod.meta.setdefault("trace_key", key)
            # content digest of the module text — the address half of the
            # tpusim.perf result cache's key (computed here, where the
            # text is already in hand, so the cache never re-reads disk)
            if not isinstance(src, Path):
                mod.meta.setdefault(
                    "content_hash",
                    hashlib.sha256(src.encode()).hexdigest()[:24],
                )
            # capture-time facts (platform, device_kind) ride on every
            # module: the cost model gates capture-backend dtype
            # normalization on the platform the trace came from
            for k in ("platform", "device_kind"):
                if k in meta:
                    mod.meta.setdefault(k, meta[k])

    cl = path / "commandlist.jsonl"
    if "commandlist.jsonl" in root_names:
        for cmd in parse_commandlist(cl):
            pod.device(cmd.device_id).commands.append(cmd)
    else:
        # traces with modules but no explicit command stream: one launch per
        # module on device 0, mirroring single-kernel SASS traces.
        for name in pod.modules:
            pod.device(0).commands.append(
                TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module=name)
            )
    return pod
