"""Parser for XLA optimized-HLO text → :mod:`tpusim.ir`.

This is the rebuild of the reference's trace parser
(``gpu-simulator/trace-parser/trace_parser.cc``): where that parses per-warp
SASS instruction lines (``inst_trace_t::parse_from_string``,
``trace_parser.cc:127``) with base+stride/base+delta address decompression,
we parse scheduled HLO text as emitted by ``jax.jit(f).lower(...).compile()
.as_text()`` — the format XLA itself round-trips.  HLO already *is* the right
IR for TPU timing (SURVEY.md §7), so no binary instrumentation or address
decompression is needed; the collective metadata the reference failed to
record (sizes, replica groups — SURVEY.md §5) is right in the op text.

The parser is pure and standalone: text in, :class:`tpusim.ir.ModuleTrace`
out.  A fast native C++ implementation with the same contract lives in
``native/``; this module is the reference implementation and fallback.
"""

from __future__ import annotations

import re

from tpusim.ir import (
    Computation,
    CollectiveInfo,
    ModuleTrace,
    TensorSpec,
    TraceOp,
    TupleSpec,
)

__all__ = ["parse_hlo_module", "parse_shape", "split_top_level"]

#: cap on distinct malformed-line samples kept in lenient-parse meta
#: (``parse_skipped_samples``) — enough to diagnose, bounded for multi-GB
#: traces where every line of a region is torn
_SKIP_SAMPLE_CAP = 8


# ---------------------------------------------------------------------------
# Low-level tokenizing helpers
# ---------------------------------------------------------------------------

_OPENERS = {"(": ")", "{": "}", "[": "]"}
_CLOSERS = {")": "(", "}": "{", "]": "["}


def split_top_level(s: str, sep: str = ",") -> list[str]:
    """Split ``s`` on ``sep`` at nesting depth 0, respecting (), {}, [] and
    double-quoted strings."""
    parts: list[str] = []
    depth = 0
    in_str = False
    start = 0
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if in_str:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c in _OPENERS:
            depth += 1
        elif c in _CLOSERS:
            depth -= 1
        elif c == sep and depth == 0:
            parts.append(s[start:i].strip())
            start = i + 1
        i += 1
    tail = s[start:].strip()
    if tail:
        parts.append(tail)
    return parts


def _find_matching(s: str, open_idx: int) -> int:
    """Index of the closer matching the opener at ``open_idx`` (respects
    quotes)."""
    opener = s[open_idx]
    closer = _OPENERS[opener]
    depth = 0
    in_str = False
    i = open_idx
    n = len(s)
    while i < n:
        c = s[i]
        if in_str:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c == opener:
            depth += 1
        elif c == closer:
            depth -= 1
            if depth == 0:
                return i
        i += 1
    raise ValueError(f"unbalanced {opener!r} in: {s[open_idx:open_idx + 80]!r}")


# ---------------------------------------------------------------------------
# Shape parsing
# ---------------------------------------------------------------------------

_SHAPE_RE = re.compile(
    r"^(?P<dtype>[a-z][a-z0-9]*)"          # bf16, f32, pred, token, ...
    r"(?:\[(?P<dims>[^\]]*)\])?"           # [256,512] ([] for scalar)
    r"(?:\{(?P<layout>[^}]*)\})?"          # {1,0:T(8,128)(2,1)S(1)}
    r"$"
)

_COMMENT_RE = re.compile(r"/\*.*?\*/")
_TILING_RE = re.compile(r"T(\([0-9,]*\))+")
_SPACE_RE = re.compile(r"S\((\d+)\)")


def parse_shape(text: str) -> TensorSpec | TupleSpec:
    """Parse one HLO shape string, e.g. ``bf16[256,512]{1,0:T(8,128)(2,1)}``
    or a tuple ``(f32[8]{0}, u32[])``."""
    text = _COMMENT_RE.sub("", text).strip()
    if text.startswith("("):
        end = _find_matching(text, 0)
        inner = text[1:end]
        parts = tuple(parse_shape(p) for p in split_top_level(inner))
        return TupleSpec(parts)
    m = _SHAPE_RE.match(text)
    if not m:
        raise ValueError(f"unparseable HLO shape: {text!r}")
    dtype = m.group("dtype")
    dims_s = m.group("dims")
    shape: tuple[int, ...] = ()
    if dims_s:
        dims = []
        for d in dims_s.split(","):
            d = d.strip().lstrip("<=")  # dynamic dims: "<=128" → bound
            if d:
                dims.append(int(d))
        shape = tuple(dims)
    layout = None
    tiling = None
    space = 0
    lay_s = m.group("layout")
    if lay_s is not None:
        # layout text: "1,0:T(8,128)(2,1)S(1)" / "1,0" / ":T(256)"
        minor, _, extras = lay_s.partition(":")
        minor = minor.strip()
        if minor:
            layout = tuple(int(x) for x in minor.split(",") if x.strip())
        if extras:
            tm = _TILING_RE.search(extras)
            if tm:
                tiling = tm.group(0)[1:]  # drop the 'T'
            sm = _SPACE_RE.search(extras)
            if sm:
                space = int(sm.group(1))
    return TensorSpec(
        dtype=dtype, shape=shape, layout=layout, tiling=tiling,
        memory_space=space,
    )


# ---------------------------------------------------------------------------
# Attribute parsing
# ---------------------------------------------------------------------------

#: attr keys whose values name other computations.
_CALLED_KEYS = (
    "calls", "to_apply", "condition", "body", "true_computation",
    "false_computation", "branch_computations", "called_computations",
    "select", "scatter",
)

_REPLICA_GROUPS_IOTA_RE = re.compile(
    r"\[(?P<dims>[0-9,]+)\]<=\[(?P<total>[0-9,]+)\]"
    r"(?:T\((?P<perm>[0-9,]+)\))?"
)


def _parse_replica_groups(val: str) -> tuple[tuple[int, ...], ...]:
    """Parse ``{{0,1},{2,3}}`` or iota form ``[2,2]<=[4]``.

    The iota form may carry a transpose suffix — ``[2,2]<=[2,2]T(1,0)``
    reshapes ``[0..4)`` to a 2x2 grid, transposes it, and reads groups
    along the last dim, yielding the STRIDED groups ``{0,2},{1,3}``
    (how XLA encodes a major-mesh-axis collective, e.g. the dp gradient
    all-reduce of a dp x tp mesh).  Group membership matters: the
    rendezvous keys of the replay driver and the mesh-axis role
    classification of ``tpusim.advise`` both read it."""
    val = val.strip()
    m = _REPLICA_GROUPS_IOTA_RE.match(val)
    if m:
        dims = [int(x) for x in m.group("dims").split(",")]
        reshape = [int(x) for x in m.group("total").split(",")]
        total = 1
        for x in reshape:
            total *= x
        ids = list(range(total))
        perm = m.group("perm")
        if perm is not None and len(reshape) > 1:
            # reshape to `reshape`, transpose by perm, then flatten:
            # out[j] = ids at the source multi-index perm-mapped from j
            axes = [int(x) for x in perm.split(",")]
            if sorted(axes) == list(range(len(reshape))):
                out_dims = [reshape[a] for a in axes]
                strides = [1] * len(reshape)
                for i in range(len(reshape) - 2, -1, -1):
                    strides[i] = strides[i + 1] * reshape[i + 1]
                flat: list[int] = []
                idx = [0] * len(out_dims)
                for _ in range(total):
                    src = sum(
                        idx[j] * strides[axes[j]]
                        for j in range(len(axes))
                    )
                    flat.append(ids[src])
                    for j in range(len(out_dims) - 1, -1, -1):
                        idx[j] += 1
                        if idx[j] < out_dims[j]:
                            break
                        idx[j] = 0
                ids = flat
        # iota groups: reshape [0..total) to dims; groups along last dim.
        group_size = dims[-1] if dims else 1
        n_groups = max(total // max(group_size, 1), 1)
        it = iter(ids)
        return tuple(
            tuple(next(it) for _ in range(group_size)) for _ in range(n_groups)
        )
    if not val.startswith("{"):
        return ()
    inner = val[1:-1].strip()
    if not inner:
        return ()
    groups = []
    for part in split_top_level(inner):
        part = part.strip()
        if part.startswith("{"):
            part = part[1:-1]
        nums = tuple(int(x) for x in part.split(",") if x.strip())
        groups.append(nums)
    return tuple(groups)


def _parse_int_set(val: str) -> tuple[int, ...]:
    val = val.strip().strip("{}")
    return tuple(int(x) for x in val.split(",") if x.strip())


def _parse_pairs(val: str) -> tuple[tuple[int, int], ...]:
    """Parse ``{{0,1},{1,2}}`` into pairs."""
    val = val.strip()
    if val.startswith("{"):
        val = val[1:-1]
    pairs = []
    for part in split_top_level(val):
        part = part.strip()
        if not part:
            continue
        nums = _parse_int_set(part)
        if len(nums) == 2:
            pairs.append((nums[0], nums[1]))
    return tuple(pairs)


def _collect_called(attrs: dict[str, str]) -> tuple[str, ...]:
    called: list[str] = []
    for key in _CALLED_KEYS:
        if key not in attrs:
            continue
        val = attrs[key].strip()
        if val.startswith("{"):
            val = val[1:-1]
        for tok in split_top_level(val):
            tok = tok.strip()
            if tok.startswith("%"):
                called.append(tok[1:])
            elif tok:
                called.append(tok)
    return tuple(called)


def _maybe_collective(opcode_base: str, attrs: dict[str, str]) -> CollectiveInfo | None:
    from tpusim.ir import COLLECTIVE_OPCODES

    if opcode_base not in COLLECTIVE_OPCODES:
        return None
    rg = ()
    if "replica_groups" in attrs:
        rg = _parse_replica_groups(attrs["replica_groups"])
    channel = None
    if "channel_id" in attrs:
        try:
            channel = int(attrs["channel_id"])
        except ValueError:
            pass
    pairs = ()
    if "source_target_pairs" in attrs:
        pairs = _parse_pairs(attrs["source_target_pairs"])
    dims = ()
    if "dimensions" in attrs:
        dims = _parse_int_set(attrs["dimensions"])
    split_dim = None
    for k in ("split_dimension", "dimension"):
        if k in attrs:
            try:
                split_dim = int(attrs[k])
            except ValueError:
                pass
            break
    return CollectiveInfo(
        kind=opcode_base,
        replica_groups=rg,
        channel_id=channel,
        use_global_device_ids=attrs.get("use_global_device_ids", "") == "true",
        source_target_pairs=pairs,
        split_dimension=split_dim,
        dimensions=dims,
    )


# ---------------------------------------------------------------------------
# Instruction-line parsing
# ---------------------------------------------------------------------------

_INSTR_RE = re.compile(
    r"^(?P<root>ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.+)$"
)

_METADATA_FIELD_RE = re.compile(r'(\w+)=(?:"((?:[^"\\]|\\.)*)"|(\S+))')


def _parse_metadata(val: str) -> dict[str, str]:
    val = val.strip()
    if val.startswith("{"):
        val = val[1:-1]
    out = {}
    for m in _METADATA_FIELD_RE.finditer(val):
        out[m.group(1)] = m.group(2) if m.group(2) is not None else m.group(3)
    return out


def _parse_operands(operand_str: str) -> tuple[str, ...]:
    """Extract operand value names from the call parens.  Tolerates both
    typed (``f32[2]{0} %a``) and untyped (``%a``) operand syntax; skips
    literals (constants) which carry no ``%``."""
    names = []
    for part in split_top_level(operand_str):
        part = part.strip()
        if not part:
            continue
        # the operand name is the last %-token in the fragment
        idx = part.rfind("%")
        if idx >= 0:
            tok = part[idx + 1:]
            tok = tok.split()[0] if tok.split() else ""
            names.append(tok.rstrip(","))
    return tuple(names)


def parse_instruction(line: str) -> TraceOp | None:
    """Parse one instruction line of a computation body.  Returns None for
    non-instruction lines (blank, comments, closing braces)."""
    line = line.strip()
    if not line or line in ("}", "{") or line.startswith("//"):
        return None
    m = _INSTR_RE.match(line)
    if not m:
        return None
    rest = m.group("rest").strip()

    # result shape: either a tuple "(...)" or "dtype[...]{...}"
    if rest.startswith("("):
        end = _find_matching(rest, 0)
        shape_text = rest[: end + 1]
        rest = rest[end + 1:].strip()
    else:
        sp = rest.find(" ")
        if sp < 0:
            return None
        shape_text = rest[:sp]
        rest = rest[sp + 1:].strip()
    result = parse_shape(shape_text)

    # opcode and its argument parens
    paren = rest.find("(")
    if paren < 0:
        return None
    opcode = rest[:paren].strip()
    close = _find_matching(rest, paren)
    operand_str = rest[paren + 1: close]
    attr_str = rest[close + 1:].lstrip(", ")

    operands = _parse_operands(operand_str)

    attrs: dict[str, str] = {}
    metadata: dict[str, str] = {}
    if attr_str:
        for tok in split_top_level(attr_str):
            if not tok:
                continue
            key, eq, val = tok.partition("=")
            key = key.strip()
            if not eq:
                attrs[key] = ""
                continue
            val = val.strip()
            if key == "metadata":
                metadata = _parse_metadata(val)
            else:
                attrs[key] = val

    from tpusim.ir import base_opcode

    if opcode == "constant":
        # preserve the literal so loop analysis can resolve scalar bounds
        attrs.setdefault("literal", operand_str.strip())
    elif opcode == "parameter":
        # preserve the index so fusion costing can map operands to params
        attrs.setdefault("param_index", operand_str.strip())

    op = TraceOp(
        name=m.group("name"),
        opcode=opcode,
        result=result,
        operands=operands,
        called=_collect_called(attrs),
        fusion_kind=attrs.get("kind"),
        collective=_maybe_collective(base_opcode(opcode), attrs),
        attrs=attrs,
        metadata=metadata,
        is_root=bool(m.group("root")),
    )
    return op


# ---------------------------------------------------------------------------
# Module-level parsing
# ---------------------------------------------------------------------------

_COMP_HEADER_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*"
    r"\((?P<params>.*)\)\s*->\s*(?P<ret>.+?)\s*\{\s*$"
)

_MODULE_RE = re.compile(r"^HloModule\s+(?P<name>[\w.\-]+)\s*(?:,\s*(?P<attrs>.*))?$")

_MODULE_INT_ATTRS = ("replica_count", "num_partitions")


def parse_module_attrs(attr_text: str, meta: dict) -> None:
    """Parse the HloModule header attr list into ``meta`` (shared by the
    Python and native parsers)."""
    for tok in split_top_level(attr_text):
        key, eq, val = tok.partition("=")
        if not eq:
            continue
        key, val = key.strip(), val.strip()
        if key in _MODULE_INT_ATTRS:
            try:
                meta[key] = int(val)
            except ValueError:
                pass
        elif key == "is_scheduled":
            meta[key] = val == "true"


def parse_hlo_module(
    text: str, name_hint: str = "module", strict: bool = True
) -> ModuleTrace:
    """Parse a full HLO module text dump into a :class:`ModuleTrace`.

    Accepts the output of ``compiled.as_text()`` (scheduled, optimized TPU
    HLO with layouts) as well as unoptimized ``lowered.as_text()`` dumps and
    hand-written fixtures.  Trailing sections (e.g. the ``FileLocations`` /
    ``StackFrames`` tables emitted by newer XLA) are ignored.

    ``strict=False`` is the salvage mode for flaky captures: a malformed
    instruction line (truncated write, corrupted shape, unbalanced
    delimiters) is SKIPPED with a counted warning instead of raising
    mid-file — one corrupt line no longer loses a whole multi-GB trace.
    The skip count lands in ``module.meta['parse_skipped_lines']`` and a
    single ``UserWarning`` summarizes the damage; repeated copies of the
    same corrupt line (a torn buffer flushed in a loop writes thousands
    of identical ones) are DEDUPLICATED — the warning and the
    ``parse_skipped_samples`` meta field carry only the distinct line
    texts (first :data:`_SKIP_SAMPLE_CAP`), with
    ``parse_skipped_distinct`` holding the distinct count.  The static
    analyzer surfaces the same damage as a warning-level ``TL012``
    diagnostic (``tpusim lint``).  Strict (raising) parsing remains the
    default: silent data loss must be opted into.
    """
    module = ModuleTrace(name=name_hint)
    current: Computation | None = None
    skipped = 0
    # distinct corrupt lines are tracked by HASH (O(1) memory per line,
    # not the line text — a multi-GB damaged region must not be held in
    # RAM); only the first few full texts are kept as samples
    skipped_hashes: set[int] = set()
    skipped_samples: list[str] = []

    for raw in text.splitlines():
        line = raw.rstrip()
        stripped = line.strip()
        if not stripped:
            continue

        # Auxiliary tables XLA interleaves into dumps (FileNames,
        # FunctionNames, FileLocations, StackFrames): a section-name line
        # followed by numbered entries.  Skip both forms outside
        # computation bodies.
        if current is None and (
            stripped in (
                "FileNames", "FunctionNames", "FileLocations", "StackFrames",
            )
            or stripped[0].isdigit()
        ):
            continue

        mm = _MODULE_RE.match(stripped)
        if mm and current is None:
            module.name = mm.group("name")
            parse_module_attrs(mm.group("attrs") or "", module.meta)
            continue

        ch = _COMP_HEADER_RE.match(stripped)
        if ch and current is None:
            current = Computation(
                name=ch.group("name"), is_entry=bool(ch.group("entry"))
            )
            continue

        if current is not None:
            if stripped == "}":
                module.add_computation(current)
                current = None
                continue
            try:
                op = parse_instruction(stripped)
            except ValueError as e:
                if strict:
                    raise ValueError(
                        f"{name_hint}: malformed HLO line "
                        f"{stripped[:120]!r}: {e}"
                    ) from e
                skipped += 1
                h = hash(stripped)
                if h not in skipped_hashes:
                    skipped_hashes.add(h)
                    if len(skipped_samples) < _SKIP_SAMPLE_CAP:
                        skipped_samples.append(
                            f"{stripped[:80]!r}: {e}"
                        )
                continue
            if op is not None:
                current.add(op)

    if current is not None:  # unterminated last computation (tolerate)
        module.add_computation(current)
    if skipped:
        import warnings

        module.meta["parse_skipped_lines"] = skipped
        module.meta["parse_skipped_distinct"] = len(skipped_hashes)
        module.meta["parse_skipped_samples"] = list(skipped_samples)
        warnings.warn(
            f"lenient HLO parse of {module.name!r}: skipped {skipped} "
            f"malformed line(s) ({len(skipped_hashes)} distinct); "
            f"first: {skipped_samples[0]}",
            UserWarning,
            stacklevel=2,
        )
    return module
