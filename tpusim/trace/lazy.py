"""Lazy per-computation HLO parsing for large traces.

Llama-70B-class optimized modules are hundreds of MB of text; eagerly
building IR objects for every computation multiplies that by the Python
object overhead (~10-30x).  The reference faces the same wall with SASS
traces and answers with on-the-fly decompression + per-kernel streaming
(``trace_parser.cc:86-125``, ``get_next_threadblock_traces``).  Here the
equivalent is structural: one cheap O(text) scan finds computation
boundaries, and each computation's ops are parsed only when the engine
first asks for it — a schedule walk touches the entry plus transitively
called computations, leaving dead weight (unreachable branches, other
partitions' variants) unparsed.

:class:`LazyModuleTrace` is a drop-in :class:`~tpusim.ir.ModuleTrace`:
``computations`` is a dict subclass that parses on first access.  Bulk
iteration (``values()``/``items()``) forces everything and is avoided by
the engine's capacity pass, which uses the raw-text ``S(1)`` scan
(:meth:`LazyModuleTrace.vmem_resident_bytes`) instead.
"""

from __future__ import annotations

import re

from tpusim.ir import FREE_OPCODES, ModuleTrace

__all__ = ["LazyModuleTrace", "parse_hlo_module_lazy", "LAZY_THRESHOLD_BYTES"]

#: load_trace switches to lazy parsing above this module-text size
LAZY_THRESHOLD_BYTES = 8 * 1024 * 1024

# a computation starts at a column-0 header: `%name (args) -> ... {` or
# `ENTRY %name ...` (optionally fused/wrapped prefixes) and ends at the
# next column-0 `}`
_COMP_HEADER_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[A-Za-z_][\w.\-]*)\s*\([^)]*\)\s*->",
    re.MULTILINE,
)
_MODULE_RE = re.compile(r"^HloModule\s+(?P<name>[\w.\-]+),?(?P<attrs>[^\n]*)")

# defining lines whose result layout pins vmem: `= dtype[dims]{...S(n)...}`
_VMEM_DEF_RE = re.compile(
    r"=\s*\(?\s*(?P<shapes>[a-z][a-z0-9]*\[[^\]]*\]\{[^}]*S\([1-9]\d*\)[^}]*\})"
)
_VMEM_SHAPE_RE = re.compile(
    r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[^\]]*)\]\{[^}]*S\([1-9]\d*\)[^}]*\}"
)
#: opcode following the result: `...} opcode(` for array results,
#: `...}) opcode(` for tuple results
_OPCODE_AFTER_SHAPE_RE = re.compile(r"[})]\s*([a-z][\w\-]*)\(")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3": 1,
    "f8e5m2": 1, "f8e4m3fn": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def _span_end(text: str, start: int) -> int:
    """Index just past the column-0 closing brace for a computation whose
    header starts at ``start``."""
    i = text.find("\n}", start)
    if i < 0:
        return len(text)
    return i + 2


class _LazyComputationDict(dict):
    """name -> Computation, parsing each span on first access."""

    def __init__(self, module: "LazyModuleTrace"):
        super().__init__()
        self._module = module

    def __missing__(self, key: str):
        span = self._module._spans.get(key)
        if span is None:
            raise KeyError(key)
        comp = self._module._parse_span(key, span)
        self[key] = comp
        return comp

    def __contains__(self, key) -> bool:  # noqa: D105
        return dict.__contains__(self, key) or key in self._module._spans

    def __iter__(self):
        return iter(self._module._spans)

    def __len__(self) -> int:
        return len(self._module._spans)

    def keys(self):  # noqa: D102
        return self._module._spans.keys()

    def values(self):  # noqa: D102 - forces full parse
        return [self[k] for k in self]

    def items(self):  # noqa: D102 - forces full parse
        return [(k, self[k]) for k in self]


class LazyModuleTrace(ModuleTrace):
    """A ModuleTrace whose computations parse on demand."""

    def __init__(self, text: str, name_hint: str = "module"):
        super().__init__(name=name_hint)
        self._text = text
        self._spans: dict[str, tuple[int, int]] = {}
        self.computations = _LazyComputationDict(self)

        m = _MODULE_RE.search(text)
        if m:
            self.name = m.group("name")
            from tpusim.trace.hlo_text import parse_module_attrs

            parse_module_attrs(m.group("attrs") or "", self.meta)
        for hm in _COMP_HEADER_RE.finditer(text):
            # only column-0 headers open computations (ops are indented)
            if hm.start() > 0 and text[hm.start() - 1] != "\n":
                continue
            name = hm.group("name")
            self._spans[name] = (hm.start(), _span_end(text, hm.start()))
            if hm.group("entry"):
                self.entry_name = name

    @property
    def parsed_count(self) -> int:
        return dict.__len__(self.computations)

    def _parse_span(self, name: str, span: tuple[int, int]):
        from tpusim.trace.native import parse_hlo_module_fast

        fragment = (
            "HloModule __lazy_fragment__\n\n" + self._text[span[0]:span[1]]
        )
        sub = parse_hlo_module_fast(fragment, name_hint="__lazy_fragment__")
        comp = sub.computations.get(name)
        if comp is None:
            # header/name normalization mismatch: take the only computation
            comps = list(sub.computations.values())
            if len(comps) != 1:
                raise KeyError(
                    f"lazy parse of {name!r} produced {len(comps)} "
                    f"computations"
                )
            comp = comps[0]
        comp.is_entry = name == self.entry_name
        return comp

    # -- cheap whole-module scans (no IR construction) ---------------------

    def vmem_resident_bytes(self) -> float:
        """Raw-text equivalent of the engine's S(1) residency walk: sum
        result-layout vmem bytes over *allocating* lines, without parsing
        any computation.  Mirrors ``_vmem_resident_bytes``'s alias rules
        (while/conditional/*-done results, non-entry dynamic-update-slice,
        and all but the destination leaf of copy-start alias existing
        buffers — see the engine docstring for the 5x-overcount this
        prevents).  Only the RESULT side of each line is scanned: operand
        references in optimized HLO text carry layouts too, and counting
        an S(1) operand mention would re-count its defining op's buffer."""
        entry_span = (
            self._spans.get(self.entry_name)
            if self.entry_name is not None else None
        )
        total = 0.0
        offset = 0  # running char offset: O(text) overall, no str.find
        for line in self._text.splitlines(keepends=True):
            idx = offset
            offset += len(line)
            dm = _VMEM_DEF_RE.search(line)
            if not dm:
                continue
            op_m = _OPCODE_AFTER_SHAPE_RE.search(line)
            opcode = op_m.group(1) if op_m else ""
            in_entry = (
                entry_span is not None
                and entry_span[0] <= idx < entry_span[1]
            )
            if opcode in FREE_OPCODES:
                # entry parameters are real allocations; nested ones alias
                if opcode != "parameter" or not in_entry:
                    continue
            if opcode in ("while", "conditional") or opcode.endswith("-done"):
                continue
            if opcode == "dynamic-update-slice" and not in_entry:
                continue
            # the opcode regex anchors on the result's closing brace —
            # keep it in the slice so the shape regex still matches
            result_side = line[:op_m.start() + 1] if op_m else line
            leaf_bytes = []
            for sm in _VMEM_SHAPE_RE.finditer(result_side):
                elems = 1
                dims = sm.group("dims").strip()
                if dims:
                    for d in dims.split(","):
                        try:
                            elems *= int(d)
                        except ValueError:
                            elems = 0
                            break
                leaf_bytes.append(
                    elems * _DTYPE_BYTES.get(sm.group("dtype"), 4)
                )
            if opcode == "copy-start":
                # result is (dst, src-alias, ctx): dst leads
                total += leaf_bytes[0] if leaf_bytes else 0.0
            elif opcode.endswith("-start"):
                # collective starts carry (operand-alias, result, ...):
                # count one buffer, not the alias pair
                total += max(leaf_bytes, default=0.0)
            else:
                total += sum(leaf_bytes)
        return total


def parse_hlo_module_lazy(
    text: str, name_hint: str = "module"
) -> LazyModuleTrace:
    return LazyModuleTrace(text, name_hint=name_hint)
