"""Lazy per-computation HLO parsing for large traces.

Llama-70B-class optimized modules are hundreds of MB of text; eagerly
building IR objects for every computation multiplies that by the Python
object overhead (~10-30x).  The reference faces the same wall with SASS
traces and answers with on-the-fly decompression + per-kernel streaming
(``trace_parser.cc:86-125``, ``get_next_threadblock_traces``).  Here the
equivalent is structural: one cheap O(text) scan finds computation
boundaries, and each computation's ops are parsed only when the engine
first asks for it — a schedule walk touches the entry plus transitively
called computations, leaving dead weight (unreachable branches, other
partitions' variants) unparsed.

:class:`LazyModuleTrace` is a drop-in :class:`~tpusim.ir.ModuleTrace`:
``computations`` is a dict subclass that parses on first access.  Bulk
iteration (``values()``/``items()``) forces everything and is avoided by
the engine's capacity pass, which uses the raw-text ``S(1)`` scan
(:meth:`LazyModuleTrace.vmem_resident_bytes`) instead.
"""

from __future__ import annotations

import re

from tpusim.ir import FREE_OPCODES, ModuleTrace

__all__ = [
    "LAZY_THRESHOLD_BYTES",
    "LazyModuleTrace",
    "STREAM_THRESHOLD_BYTES",
    "StreamingModuleTrace",
    "parse_hlo_module_lazy",
    "parse_hlo_module_streaming",
]

#: load_trace switches to lazy parsing above this module-text size
LAZY_THRESHOLD_BYTES = 8 * 1024 * 1024

# a computation starts at a column-0 header: `%name (args) -> ... {` or
# `ENTRY %name ...` (optionally fused/wrapped prefixes) and ends at the
# next column-0 `}`.  The parameter list may contain NESTED parens
# (tuple-typed parameters: `%body (arg: (s32[], bf16[...])) -> ...`)
# and may wrap across lines, so the open is matched by regex and the
# close by a balanced-paren scan (see _match_header).
_COMP_HEAD_OPEN_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[A-Za-z_][\w.\-]*)\s*\(",
    re.MULTILINE,
)

#: headers longer than this are not headers (balanced-scan cap)
_HEADER_SCAN_CAP = 1 << 20


def _match_header(text: str, start: int = 0):
    """Does ``text[start:]`` begin a computation header
    (``name(params) ->``, params possibly nested/multi-line)?

    Returns ``(name, is_entry)`` on a confirmed header, ``None`` when
    it definitely isn't one, or the string ``"partial"`` when the text
    ends before the parameter list closes (a streaming caller should
    buffer more lines and retry)."""
    m = _COMP_HEAD_OPEN_RE.match(text, start)
    if not m:
        return None
    depth = 0
    limit = min(len(text), m.end() + _HEADER_SCAN_CAP)
    for k in range(m.end() - 1, limit):
        c = text[k]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                rest = text[k + 1:k + 64].lstrip()
                if rest.startswith("->"):
                    return m.group("name"), bool(m.group("entry"))
                if not rest:
                    # params closed at end-of-available-text: the ->
                    # may be on a line the caller hasn't buffered yet
                    return "partial"
                return None
    return "partial" if limit >= len(text) else None
_MODULE_RE = re.compile(r"^HloModule\s+(?P<name>[\w.\-]+),?(?P<attrs>[^\n]*)")

# cheap filter for lines that can possibly pin vmem: a definition
# (`=`) mentioning an `S(n)` layout anywhere.  Deliberately broad — a
# tuple result whose FIRST leaf is an HBM alias but whose second leaf
# is the S(1) allocation (async starts: (operand-alias, result, ...))
# must still be scanned; the result-side leaf walk below decides what
# actually counts (matmul_chain's slice-start ops were invisible to
# the old first-leaf-anchored regex, under-counting residency vs the
# engine's IR walk)
_VMEM_DEF_RE = re.compile(r"=.*S\([1-9]\d*\)")
#: every result leaf, positionally (layout optional — an HBM alias leaf
#: still occupies its tuple slot, which the copy-start rule needs)
_VMEM_SHAPE_RE = re.compile(
    r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[^\]]*)\](?:\{(?P<layout>[^}]*)\})?"
)
_VMEM_SPACE_RE = re.compile(r"S\([1-9]\d*\)")
#: opcode following the result: `...} opcode(` for array results,
#: `...}) opcode(` for tuple results
_OPCODE_AFTER_SHAPE_RE = re.compile(r"[})]\s*([a-z][\w\-]*)\(")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3": 1,
    "f8e5m2": 1, "f8e4m3fn": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def _span_end(text: str, start: int) -> int:
    """Index just past the column-0 closing brace for a computation whose
    header starts at ``start``."""
    i = text.find("\n}", start)
    if i < 0:
        return len(text)
    return i + 2


class _LazyComputationDict(dict):
    """name -> Computation, parsing each span on first access."""

    def __init__(self, module: "LazyModuleTrace"):
        super().__init__()
        self._module = module

    def __missing__(self, key: str):
        span = self._module._spans.get(key)
        if span is None:
            raise KeyError(key)
        comp = self._module._parse_span(key, span)
        self[key] = comp
        return comp

    def __contains__(self, key) -> bool:  # noqa: D105
        return dict.__contains__(self, key) or key in self._module._spans

    def __iter__(self):
        return iter(self._module._spans)

    def __len__(self) -> int:
        return len(self._module._spans)

    def keys(self):  # noqa: D102
        return self._module._spans.keys()

    def values(self):  # noqa: D102 - forces full parse
        return [self[k] for k in self]

    def items(self):  # noqa: D102 - forces full parse
        return [(k, self[k]) for k in self]


class LazyModuleTrace(ModuleTrace):
    """A ModuleTrace whose computations parse on demand.

    Even the computation *span index* (one regex pass over the whole
    text) builds lazily: a module priced from the durable compile tier
    (warm ``.cmod`` columns carrying the entry name) never needs to
    know where its computations live, so first-touch latency pays only
    the module-header parse.  Anything that asks — ``entry_name`` on an
    unindexed module, any ``computations`` access — forces the index
    exactly once."""

    #: class-level defaults so the entry_name property (a data
    #: descriptor, which shadows the dataclass field) works during
    #: ModuleTrace.__init__'s own assignment
    _entry_name: str | None = None
    _spans_cache: dict | None = None

    def __init__(self, text: str, name_hint: str = "module"):
        super().__init__(name=name_hint)
        self._text = text
        self.computations = _LazyComputationDict(self)

        m = _MODULE_RE.search(text)
        if m:
            self.name = m.group("name")
            from tpusim.trace.hlo_text import parse_module_attrs

            parse_module_attrs(m.group("attrs") or "", self.meta)

    @property
    def entry_name(self) -> str | None:
        if self._entry_name is None and self._spans_cache is None:
            self._build_spans()
        return self._entry_name

    @entry_name.setter
    def entry_name(self, value) -> None:
        self._entry_name = value

    @property
    def _spans(self) -> dict[str, tuple[int, int]]:
        spans = self._spans_cache
        if spans is None:
            spans = self._build_spans()
        return spans

    def _build_spans(self) -> dict[str, tuple[int, int]]:
        text = self._text
        spans: dict[str, tuple[int, int]] = {}
        for hm in _COMP_HEAD_OPEN_RE.finditer(text):
            # only column-0 headers open computations (ops are indented)
            if hm.start() > 0 and text[hm.start() - 1] != "\n":
                continue
            got = _match_header(text, hm.start())
            if not isinstance(got, tuple):
                continue
            name, is_entry = got
            spans[name] = (hm.start(), _span_end(text, hm.start()))
            if is_entry:
                self._entry_name = name
        self._spans_cache = spans
        return spans

    @property
    def parsed_count(self) -> int:
        return dict.__len__(self.computations)

    def _parse_span(self, name: str, span: tuple[int, int]):
        from tpusim.trace.native import parse_hlo_module_fast

        fragment = (
            "HloModule __lazy_fragment__\n\n" + self._text[span[0]:span[1]]
        )
        sub = parse_hlo_module_fast(fragment, name_hint="__lazy_fragment__")
        comp = sub.computations.get(name)
        if comp is None:
            # header/name normalization mismatch: take the only computation
            comps = list(sub.computations.values())
            if len(comps) != 1:
                raise KeyError(
                    f"lazy parse of {name!r} produced {len(comps)} "
                    f"computations"
                )
            comp = comps[0]
        comp.is_entry = name == self.entry_name
        return comp

    # -- cheap whole-module scans (no IR construction) ---------------------

    def vmem_resident_bytes(self) -> float:
        """Raw-text equivalent of the engine's S(1) residency walk: sum
        result-layout vmem bytes over *allocating* lines, without parsing
        any computation.  Mirrors ``_vmem_resident_bytes``'s alias rules
        (while/conditional/*-done results, non-entry dynamic-update-slice,
        and all but the destination leaf of copy-start alias existing
        buffers — see the engine docstring for the 5x-overcount this
        prevents).  Only the RESULT side of each line is scanned: operand
        references in optimized HLO text carry layouts too, and counting
        an S(1) operand mention would re-count its defining op's buffer."""
        entry_span = (
            self._spans.get(self.entry_name)
            if self.entry_name is not None else None
        )

        def lines():
            offset = 0  # running char offset: O(text), no str.find
            for line in self._text.splitlines(keepends=True):
                yield offset, line
                offset += len(line)

        return _residency_scan(lines(), entry_span)


def parse_hlo_module_lazy(
    text: str, name_hint: str = "module"
) -> LazyModuleTrace:
    return LazyModuleTrace(text, name_hint=name_hint)


def _residency_scan(lines, entry_span: tuple[int, int] | None) -> float:
    """The S(1) residency line scan shared by the in-memory lazy module
    and the file-backed streaming module; ``lines`` yields
    ``(char_offset, line)`` pairs.  Alias rules mirror the engine's
    ``_vmem_resident_bytes`` (see :meth:`LazyModuleTrace.
    vmem_resident_bytes` for the full contract)."""
    total = 0.0
    for idx, line in lines:
        dm = _VMEM_DEF_RE.search(line)
        if not dm:
            continue
        op_m = _OPCODE_AFTER_SHAPE_RE.search(line)
        if op_m is None:
            # no `shape opcode(` structure: a wrapped header line or
            # degenerate text, never an allocating definition
            continue
        opcode = op_m.group(1)
        in_entry = (
            entry_span is not None
            and entry_span[0] <= idx < entry_span[1]
        )
        if opcode in FREE_OPCODES:
            # entry parameters are real allocations; nested ones alias
            if opcode != "parameter" or not in_entry:
                continue
        if opcode in ("while", "conditional", "call") \
                or opcode.endswith("-done"):
            continue
        if opcode == "dynamic-update-slice" and not in_entry:
            continue
        # the opcode regex anchors on the result's closing brace —
        # keep it in the slice so the shape regex still matches
        result_side = line[:op_m.start() + 1]
        leaves = []  # (bytes, is_vmem) per result leaf, positionally
        for sm in _VMEM_SHAPE_RE.finditer(result_side):
            layout = sm.group("layout")
            vmem = bool(layout and _VMEM_SPACE_RE.search(layout))
            elems = 1
            dims = sm.group("dims").strip()
            if dims:
                for d in dims.split(","):
                    try:
                        elems *= int(d)
                    except ValueError:
                        elems = 0
                        break
            leaves.append(
                (elems * _DTYPE_BYTES.get(sm.group("dtype"), 4), vmem)
            )
        if opcode == "copy-start":
            # result is (dst, src-alias, ctx): only a vmem DST leaf is
            # a new allocation — an S(1) src alias must not re-count
            if leaves and leaves[0][1]:
                total += leaves[0][0]
        elif opcode.endswith("-start"):
            # collective starts carry (operand-alias, result, ...):
            # count one buffer, not the alias pair
            total += max(
                (b for b, vmem in leaves if vmem), default=0.0
            )
        else:
            total += sum(b for b, vmem in leaves if vmem)
    return total


# ---------------------------------------------------------------------------
# Streaming (file-backed) modules — bounded-RSS pricing for multi-GB pods
# ---------------------------------------------------------------------------

#: load_trace switches from in-memory lazy parsing to file-backed
#: streaming above this module-text size (override with
#: $TPUSIM_STREAM_THRESHOLD; plain .hlo files only — gzipped modules
#: decompress to memory and take the lazy path)
STREAM_THRESHOLD_BYTES = 64 * 1024 * 1024

#: substrings whose presence makes a module's price topology-dependent
#: (mirror of tpusim.perf.cache._COLLECTIVE_MARKERS, scanned during the
#: index pass so the result cache never forces a full parse)
_ICI_MARKERS = (
    b"all-reduce", b"all-gather", b"reduce-scatter", b"all-to-all",
    b"collective-permute", b"collective-broadcast",
)
_ICI_OVERLAP = max(len(m) for m in _ICI_MARKERS) - 1

_INDEX_CHUNK = 4 * 1024 * 1024

_libc = None
_libc_tried = False


def _malloc_trim() -> None:
    """Best-effort glibc heap trim (no-op off glibc/Linux)."""
    global _libc, _libc_tried
    if not _libc_tried:
        _libc_tried = True
        try:
            import ctypes

            lib = ctypes.CDLL("libc.so.6", use_errno=False)
            lib.malloc_trim.argtypes = [ctypes.c_size_t]
            _libc = lib
        except (OSError, AttributeError):
            _libc = None
    if _libc is not None:
        try:
            _libc.malloc_trim(0)
        except OSError:
            pass


class _StreamingComputationDict(_LazyComputationDict):
    """Parse-on-demand with bounded retention: at most ``cap`` parsed
    computations stay resident; the oldest parse is dropped when a new
    one would exceed it (spans persist, so an evicted computation simply
    re-parses on its next access)."""

    def __init__(self, module: "StreamingModuleTrace", cap: int):
        super().__init__(module)
        self._cap = max(int(cap), 1)

    def __missing__(self, key: str):
        comp = super().__missing__(key)
        while dict.__len__(self) > self._cap:
            oldest = next(dict.__iter__(self))
            if oldest == key:
                break
            dict.__delitem__(self, oldest)
        return comp


class StreamingModuleTrace(ModuleTrace):
    """A ModuleTrace backed by an on-disk HLO file.

    One chunked pass over the file builds the computation span index,
    the content hash, and the ICI-marker flag **without ever holding the
    full text**; computations parse on demand by seeking their span and
    at most ``parsed_cap`` stay resident.  The fastpath prices such
    modules *lean* (``stream_lean``): each reached computation is
    compiled to flat columns and its parsed IR released immediately, so
    peak RSS is bounded by the span index + columns + a handful of
    parsed computations — far below the trace size."""

    #: marks this module for lean fastpath compilation (per-op
    #: aggregates dropped — their name table is the O(trace) term)
    stream_lean = True

    def __init__(self, path, name_hint: str = "module",
                 parsed_cap: int = 8):
        import hashlib

        super().__init__(name=name_hint)
        self._path = str(path)
        self._spans: dict[str, tuple[int, int]] = {}
        self.computations = _StreamingComputationDict(self, parsed_cap)

        h = hashlib.sha256()
        uses_ici = False
        self._open_name: str | None = None
        self._open_start = 0
        self._header_seen = False
        # multi-line computation headers (long parameter lists wrap):
        # a column-0 line that *starts* like a header but doesn't match
        # the full pattern buffers continuation lines until the pattern
        # completes (mirrors the in-memory regex, whose [^)]* spans
        # newlines)
        self._pending: str | None = None
        self._pending_start = 0
        offset = 0
        with open(self._path, "rb") as f:
            carry = b""
            while True:
                chunk = f.read(_INDEX_CHUNK)
                if not chunk:
                    break
                h.update(chunk)
                if not uses_ici:
                    uses_ici = any(
                        m in carry[-_ICI_OVERLAP:] + chunk
                        for m in _ICI_MARKERS
                    )
                buf = carry + chunk
                lines = buf.split(b"\n")
                carry = lines.pop()  # partial trailing line
                for raw in lines:
                    self._index_line(raw, offset)
                    offset += len(raw) + 1
            if carry:
                self._index_line(carry, offset)
                offset += len(carry)
            if self._open_name is not None:
                # unterminated final computation: span to EOF
                self._spans[self._open_name] = (self._open_start, offset)
        del self._pending, self._pending_start
        del self._open_name, self._open_start, self._header_seen
        self.meta.setdefault("content_hash", h.hexdigest()[:24])
        self._uses_ici_cache = uses_ici

    #: a header's parameter list may wrap, but not without bound — drop
    #: a pending candidate past this many buffered chars (not a header)
    _PENDING_CAP = 1 << 20

    def _index_line(self, raw: bytes, offset: int) -> None:
        """One line of the index pass (state machine over column-0
        structure; ``raw`` has no trailing newline)."""
        if self._pending is not None:
            self._pending += "\n" + raw.decode("utf-8", errors="replace")
            got = _match_header(self._pending)
            if isinstance(got, tuple):
                self._open_name, is_entry = got[0], got[1]
                self._open_start = self._pending_start
                if is_entry:
                    self.entry_name = self._open_name
                self._pending = None
            elif got is None or len(self._pending) > self._PENDING_CAP:
                # the parameter list closed without the header pattern
                # completing (or grew absurd): not a computation header
                self._pending = None
            return
        if not self._header_seen and raw.startswith(b"HloModule"):
            self._header_seen = True
            m = _MODULE_RE.match(raw.decode("utf-8", errors="replace"))
            if m:
                self.name = m.group("name")
                from tpusim.trace.hlo_text import parse_module_attrs

                parse_module_attrs(m.group("attrs") or "", self.meta)
        elif raw[:1] == b"}":
            if self._open_name is not None:
                self._spans[self._open_name] = (
                    self._open_start, offset + len(raw),
                )
                self._open_name = None
        elif raw[:1] not in (b"", b" ", b"\t"):
            text = raw.decode("utf-8", errors="replace")
            got = _match_header(text)
            if isinstance(got, tuple):
                self._open_name, is_entry = got[0], got[1]
                self._open_start = offset
                if is_entry:
                    self.entry_name = self._open_name
            elif got == "partial":
                self._pending = text
                self._pending_start = offset

    @property
    def parsed_count(self) -> int:
        return dict.__len__(self.computations)

    _releases = 0

    def release_computation(self, name: str) -> None:
        """Drop a parsed computation's IR (the fastpath calls this right
        after compiling it to columns; the span survives, so a later
        access simply re-parses).  Every few releases the glibc heap is
        trimmed: parse churn routes the >512-byte metadata strings
        through malloc, whose freed chunks otherwise sit in arena free
        lists and count against the bounded-RSS contract."""
        if dict.__contains__(self.computations, name):
            dict.__delitem__(self.computations, name)
        self._releases += 1
        if self._releases % 8 == 0:
            _malloc_trim()

    def _read_span(self, span: tuple[int, int]) -> str:
        with open(self._path, "rb") as f:
            f.seek(span[0])
            return f.read(span[1] - span[0]).decode(
                "utf-8", errors="replace"
            )

    def _parse_span(self, name: str, span: tuple[int, int]):
        from tpusim.trace.native import parse_hlo_module_fast

        fragment = (
            "HloModule __lazy_fragment__\n\n" + self._read_span(span)
        )
        sub = parse_hlo_module_fast(fragment, name_hint="__lazy_fragment__")
        comp = sub.computations.get(name)
        if comp is None:
            comps = list(sub.computations.values())
            if len(comps) != 1:
                raise KeyError(
                    f"streaming parse of {name!r} produced {len(comps)} "
                    f"computations"
                )
            comp = comps[0]
        comp.is_entry = name == self.entry_name
        return comp

    def vmem_resident_bytes(self) -> float:
        """Chunk-streamed S(1) residency scan (same contract as the
        in-memory lazy scan; the file is read once, never held)."""
        entry_span = (
            self._spans.get(self.entry_name)
            if self.entry_name is not None else None
        )

        def lines():
            offset = 0
            with open(self._path, "rb") as f:
                carry = b""
                while True:
                    chunk = f.read(_INDEX_CHUNK)
                    if not chunk:
                        break
                    buf = carry + chunk
                    parts = buf.split(b"\n")
                    carry = parts.pop()
                    for raw in parts:
                        yield offset, raw.decode(
                            "utf-8", errors="replace"
                        )
                        offset += len(raw) + 1
                if carry:
                    yield offset, carry.decode("utf-8", errors="replace")

        return _residency_scan(lines(), entry_span)


def parse_hlo_module_streaming(
    path, name_hint: str = "module", parsed_cap: int = 8
) -> StreamingModuleTrace:
    return StreamingModuleTrace(path, name_hint=name_hint,
                                parsed_cap=parsed_cap)
