"""While-loop trip-count inference.

XLA records ``known_trip_count`` in the while op's ``backend_config`` on some
backends, but not all (the axon TPU backend omits it).  ``lax.scan`` /
``fori_loop`` loops still follow a canonical induction pattern in HLO:

* the loop carry is a tuple with an ``s32`` induction variable at index *i*;
* the condition computation's root is ``compare(gte_i(param), constant)``;
* the body's root tuple carries ``add(gte_i(param), constant_step)`` at *i*.

This pass recovers the trip count from that pattern — the structural
analogue of the reference's kernel-header parsing (grid dims from the trace
header, ``trace_parser.cc:299``): without it a traced loop would be timed as
a single iteration.
"""

from __future__ import annotations

import re

from tpusim.ir import Computation, ModuleTrace, TraceOp

__all__ = ["infer_trip_count"]

_PASSTHROUGH = ("copy", "convert", "bitcast", "bitcast-convert", "reshape")

_INT_LITERAL_RE = re.compile(r"-?\d+")


def _chase(comp: Computation, name: str, depth: int = 0) -> TraceOp | None:
    """Follow copy/convert chains to the defining op."""
    if depth > 8 or not comp.has_op(name):
        return None
    op = comp.op(name)
    if op.base in _PASSTHROUGH and op.operands:
        return _chase(comp, op.operands[0], depth + 1)
    return op


def _scalar_const(comp: Computation, name: str) -> int | None:
    op = _chase(comp, name)
    if op is None or op.opcode != "constant":
        return None
    m = _INT_LITERAL_RE.search(op.attrs.get("literal", ""))
    return int(m.group(0)) if m else None


def _gte_index(comp: Computation, name: str) -> int | None:
    op = _chase(comp, name)
    if op is None:
        return None
    if op.opcode == "get-tuple-element":
        try:
            return int(op.attrs.get("index", ""))
        except ValueError:
            return None
    return None


def _tuple_element(comp: Computation, tuple_name: str, idx: int) -> str | None:
    op = _chase(comp, tuple_name)
    if op is None or op.base != "tuple" or idx >= len(op.operands):
        return None
    return op.operands[idx]


def infer_trip_count(
    module: ModuleTrace,
    comp: Computation,
    while_op: TraceOp,
    default: int = 1,
) -> int:
    """Trip count of ``while_op`` (which lives in ``comp``), or ``default``."""
    cond_name = while_op.attrs.get("condition", "").lstrip("%")
    body_name = while_op.attrs.get("body", "").lstrip("%")
    if cond_name not in module.computations or body_name not in module.computations:
        return default
    cond = module.computation(cond_name)
    body = module.computation(body_name)

    root = _chase(cond, cond.root.name)
    if root is None or root.base != "compare" or len(root.operands) != 2:
        return default
    direction = root.attrs.get("direction", "LT")

    # which side is the induction variable?
    idx = _gte_index(cond, root.operands[0])
    bound = _scalar_const(cond, root.operands[1])
    flipped = False
    if idx is None:
        idx = _gte_index(cond, root.operands[1])
        bound = _scalar_const(cond, root.operands[0])
        flipped = True
    if idx is None or bound is None:
        return default

    # start value: while's init tuple element at idx
    if not while_op.operands:
        return default
    init_name = _tuple_element(comp, while_op.operands[0], idx)
    start = _scalar_const(comp, init_name) if init_name else None
    if start is None:
        return default

    # step: body root tuple element at idx = add(gte_idx, const)
    body_elem_name = _tuple_element(body, body.root.name, idx)
    if body_elem_name is None:
        return default
    upd = _chase(body, body_elem_name)
    if upd is None or upd.base not in ("add", "subtract"):
        return default
    step = None
    for operand in upd.operands:
        c = _scalar_const(body, operand)
        if c is not None:
            step = -c if upd.base == "subtract" else c
            break
    if step is None or step == 0:
        return default

    # normalize: iv on the left of the comparison
    if flipped:
        direction = {"LT": "GT", "GT": "LT", "LE": "GE", "GE": "LE"}.get(
            direction, direction
        )

    span = None
    if direction == "LT" and step > 0:
        span = bound - start
    elif direction == "LE" and step > 0:
        span = bound - start + 1
    elif direction == "GT" and step < 0:
        span = start - bound
        step = -step
    elif direction == "GE" and step < 0:
        span = start - bound + 1
        step = -step
    if span is None or span <= 0:
        return default if span is None else 0
    return max((span + step - 1) // step, 0)
