"""ctypes bridge to the native HLO scanner (``native/hlo_scan.cpp``).

The structural pass (line classification, balanced-delimiter splitting,
operand extraction) runs in C++; this module rebuilds :mod:`tpusim.ir`
objects from the pre-split record stream.  Falls back transparently to the
pure-Python parser when the shared library hasn't been built — the two
paths are contract-tested against each other (tests/test_native.py).

Build with ``make -C native``.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

from tpusim.ir import Computation, ModuleTrace, TraceOp
from tpusim.trace import hlo_text as pyparse

__all__ = ["native_available", "parse_hlo_module_native", "parse_hlo_module_fast"]

_RS = "\x1e"
_US = "\x1f"
#: sub-field separator of the v2 (parse-to-columns) attr-token field
_GS = "\x1d"

_LIB: ctypes.CDLL | None = None
_LIB_TRIED = False
#: True when the library also exports the v2 parse-to-columns scan
_HAS_V2 = False


def _lib_path() -> Path:
    return (
        Path(__file__).resolve().parent.parent.parent
        / "native" / "libtpusim_native.so"
    )


def load_shared_lib() -> ctypes.CDLL | None:
    """Open ``libtpusim_native.so`` (honoring ``TPUSIM_NO_NATIVE``) with no
    symbol setup — shared by every native consumer; each declares and
    version-checks its own entry points."""
    path = _lib_path()
    if not path.exists() or os.environ.get("TPUSIM_NO_NATIVE"):
        return None
    try:
        return ctypes.CDLL(str(path))
    except OSError:
        return None


def _load() -> ctypes.CDLL | None:
    global _LIB, _LIB_TRIED, _HAS_V2
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    lib = load_shared_lib()
    if lib is None:
        return None
    try:
        lib.hlo_scan.restype = ctypes.POINTER(ctypes.c_char)
        lib.hlo_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hlo_scan_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
        lib.hlo_scan_abi_version.restype = ctypes.c_int
        if lib.hlo_scan_abi_version() != 1:
            return None
        _LIB = lib
    except (OSError, AttributeError):
        return None
    try:
        # the v2 (parse-to-columns) scan is optional: an older library
        # without it still serves the v1 record stream
        lib.hlo_scan2.restype = ctypes.POINTER(ctypes.c_char)
        lib.hlo_scan2.argtypes = lib.hlo_scan.argtypes
        lib.hlo_scan2_abi_version.restype = ctypes.c_int
        _HAS_V2 = lib.hlo_scan2_abi_version() == 1
    except (OSError, AttributeError):
        _HAS_V2 = False
    return _LIB


def native_available() -> bool:
    return _load() is not None


def _scan(text: str, v2: bool = False) -> str:
    lib = _load()
    assert lib is not None
    raw = text.encode("utf-8", errors="replace")
    out_len = ctypes.c_uint64(0)
    entry = lib.hlo_scan2 if v2 else lib.hlo_scan
    ptr = entry(raw, len(raw), ctypes.byref(out_len))
    if not ptr:
        raise MemoryError("hlo_scan allocation failed")
    try:
        return ctypes.string_at(ptr, out_len.value).decode(
            "utf-8", errors="replace"
        )
    finally:
        lib.hlo_scan_free(ptr)


def parse_hlo_module_native(text: str, name_hint: str = "module") -> ModuleTrace:
    """Parse using the native scanner (raises if unavailable).

    With a library exporting the v2 parse-to-columns scan, shapes
    arrive pre-parsed and attrs pre-split — IR assembly then runs no
    regex and no balanced-delimiter splitting (byte-identical modules
    either way, pinned by tests/test_native.py)."""
    v2 = _load() is not None and _HAS_V2
    stream = _scan(text, v2=v2)
    build = _build_op2 if v2 else _build_op
    module = ModuleTrace(name=name_hint)
    current: Computation | None = None

    for record in stream.split(_RS):
        if not record:
            continue
        fields = record.split(_US)
        kind = fields[0]
        if kind == "M":
            module.name = fields[1] or name_hint
            attr_text = fields[2] if len(fields) > 2 else ""
            pyparse.parse_module_attrs(attr_text, module.meta)
        elif kind == "C":
            current = Computation(name=fields[1], is_entry=fields[2] == "1")
        elif kind == "E":
            if current is not None:
                module.add_computation(current)
            current = None
        elif kind == "I" and current is not None:
            current.add(build(fields))
    if current is not None:
        module.add_computation(current)
    return module


def _finish_op(
    fields: list[str], result, attrs: dict, metadata: dict
) -> TraceOp:
    """Shared tail of the v1/v2 op builders (identical by contract)."""
    from tpusim.ir import base_opcode

    opcode = fields[4]
    literal = fields[7] if len(fields) > 7 else ""
    if opcode == "constant" and literal:
        attrs.setdefault("literal", literal)
    elif opcode == "parameter" and literal:
        attrs.setdefault("param_index", literal)
    return TraceOp(
        name=fields[1],
        opcode=opcode,
        result=result,
        operands=tuple(o for o in fields[5].split(",") if o),
        called=pyparse._collect_called(attrs),
        fusion_kind=attrs.get("kind"),
        collective=pyparse._maybe_collective(base_opcode(opcode), attrs),
        attrs=attrs,
        metadata=metadata,
        is_root=fields[2] == "1",
    )


def _build_op(fields: list[str]) -> TraceOp:
    # I, name, root, shape, opcode, operands, attrs, literal
    result = pyparse.parse_shape(fields[3])
    attr_text = fields[6] if len(fields) > 6 else ""
    attrs: dict[str, str] = {}
    metadata: dict[str, str] = {}
    if attr_text:
        for tok in pyparse.split_top_level(attr_text):
            if not tok:
                continue
            key, eq, val = tok.partition("=")
            key = key.strip()
            if not eq:
                attrs[key] = ""
            elif key == "metadata":
                metadata = pyparse._parse_metadata(val.strip())
            else:
                attrs[key] = val.strip()
    return _finish_op(fields, result, attrs, metadata)


def _decode_shape(enc: str):
    """Rebuild a shape from the v2 scan's prefix token stream (see the
    hlo_scan.cpp header comment for the grammar)."""
    from tpusim.ir import TensorSpec, TupleSpec

    tokens = enc.split(";")
    pos = 0

    def build():
        nonlocal pos
        tok = tokens[pos]
        pos += 1
        if tok.startswith("("):
            n = int(tok[1:])
            return TupleSpec(tuple(build() for _ in range(n)))
        dtype, dims, layout, tiling, space = tok.split(":")
        return TensorSpec(
            dtype=dtype,
            shape=(
                tuple(int(d) for d in dims.split(",")) if dims else ()
            ),
            layout=(
                None if layout == "n"
                else tuple(int(x) for x in layout.split(","))
            ),
            tiling=None if tiling == "n" else tiling,
            memory_space=int(space),
        )

    return build()


def _build_op2(fields: list[str]) -> TraceOp:
    # I, name, root, shape_enc, opcode, operands, attr_tokens, literal —
    # shapes decoded from pre-parsed numerics ('!' = per-shape fallback
    # to the reference parser, same error semantics), attr tokens
    # pre-split at depth 0 by the C++ pass
    shape_enc = fields[3]
    if shape_enc.startswith("!"):
        result = pyparse.parse_shape(shape_enc[1:])
    else:
        result = _decode_shape(shape_enc)
    attr_field = fields[6] if len(fields) > 6 else ""
    attrs: dict[str, str] = {}
    metadata: dict[str, str] = {}
    if attr_field:
        for tok in attr_field.split(_GS):
            key, eq, val = tok.partition("=")
            key = key.strip()
            if not eq:
                attrs[key] = ""
            elif key == "metadata":
                metadata = pyparse._parse_metadata(val.strip())
            else:
                attrs[key] = val.strip()
    return _finish_op(fields, result, attrs, metadata)


def parse_hlo_module_fast(
    text: str, name_hint: str = "module", strict: bool = True
) -> ModuleTrace:
    """Native parse when the library is built, Python otherwise.

    ``strict=False`` (skip malformed lines with a counted warning) always
    takes the Python path: the C++ scanner's record stream has no
    per-line error recovery, and salvage mode is for damaged captures
    where robustness beats speed."""
    if strict and native_available():
        return parse_hlo_module_native(text, name_hint)
    return pyparse.parse_hlo_module(text, name_hint, strict=strict)
