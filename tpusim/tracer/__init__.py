"""Live-capture frontend: JAX workloads → stored traces.

The rebuild of the reference's tracer stack (``util/tracer_nvbit/``): where
that LD_PRELOADs an NVBit tool to instrument every SASS instruction on a real
GPU (``tracer_tool.cu``) and post-processes raw records into ``.traceg``
files, we ask XLA for the artifact it already has — the scheduled, optimized
HLO of a compiled executable — plus its cost analysis and (optionally) real
execution timings for correlation.  No binary instrumentation is needed;
``jit → lower → compile`` is the capture point, and it works identically on
a TPU-VM or a CPU host (the CPU path is this framework's "trace download"
substitute for fixtures, cf. ``get-accel-sim-traces.py``).
"""

from tpusim.tracer.capture import Capture, capture, capture_to_dir, measure_wall_time

__all__ = ["Capture", "capture", "capture_to_dir", "measure_wall_time"]
