"""JAX → trace capture.

Capture pipeline (mirror of ``tracer_tool.cu`` + ``post-traces-processing``):

1. ``jax.jit(fn).lower(*args)`` — tracing (the instrumentation point; this is
   where NVBit would inject callbacks, ``tracer_tool.cu:130-275``).
2. ``.compile()`` — XLA optimizes + schedules; ``compiled.as_text()`` is the
   per-device program the hardware runs, with layouts, fusions and async
   collective pairs.  This text is the trace body (the ``.traceg``).
3. ``compiled.cost_analysis()`` — XLA's own flops/bytes accounting, stored in
   the trace meta as ground truth for the cost model's unit tests.
4. (optional, on real hardware) timed execution — the correlation target,
   standing in for ``util/hw_stats/run_hw.py``'s nvprof pass.

The capture honors ``TPUSIM_TRACE_DEVICE`` the way the fork's tracer honors
``GPU_TRACE_ID`` (``tracer_tool.cu:115-116,303-316``): in a multi-device
process, trace only that device's view (SPMD programs are identical across
devices, so one program + the topology is the whole pod trace).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Sequence

from tpusim.ir import CommandKind, ModuleTrace, TraceCommand
from tpusim.trace.format import TraceDir, save_trace

__all__ = ["Capture", "capture", "capture_to_dir", "measure_wall_time"]


def _tree_bytes(tree: Any) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * leaf.dtype.itemsize
    return total


@dataclass
class Capture:
    """One captured module + its metadata; convertible to IR or disk."""

    name: str
    hlo_text: str
    meta: dict[str, Any] = field(default_factory=dict)
    in_bytes: int = 0
    out_bytes: int = 0

    _module: ModuleTrace | None = field(default=None, repr=False)

    @property
    def module(self) -> ModuleTrace:
        if self._module is None:
            from tpusim.trace.lazy import (
                LAZY_THRESHOLD_BYTES, parse_hlo_module_lazy,
            )
            from tpusim.trace.native import parse_hlo_module_fast

            if len(self.hlo_text) >= LAZY_THRESHOLD_BYTES:
                self._module = parse_hlo_module_lazy(
                    self.hlo_text, name_hint=self.name
                )
            else:
                self._module = parse_hlo_module_fast(
                    self.hlo_text, name_hint=self.name
                )
            self._module.meta.update(self.meta)
        return self._module

    def commands(self, device_id: int = 0, stream_id: int = 0) -> list[TraceCommand]:
        """The command-stream entries for one launch of this capture:
        H2D memcpys for inputs, the kernel launch, D2H for outputs —
        the shape of a ``kernelslist.g`` entry set
        (``trace_parser.cc:220-297``)."""
        cmds = []
        if self.in_bytes:
            cmds.append(TraceCommand(
                kind=CommandKind.MEMCPY_H2D, stream_id=stream_id,
                device_id=device_id, nbytes=self.in_bytes,
            ))
        cmds.append(TraceCommand(
            kind=CommandKind.KERNEL_LAUNCH, stream_id=stream_id,
            device_id=device_id, module=self.name,
        ))
        if self.out_bytes:
            cmds.append(TraceCommand(
                kind=CommandKind.MEMCPY_D2H, stream_id=stream_id,
                device_id=device_id, nbytes=self.out_bytes,
            ))
        return cmds


def capture(
    fn: Callable,
    *args: Any,
    name: str | None = None,
    static_argnums: Sequence[int] = (),
    donate_argnums: Sequence[int] = (),
    jit_kwargs: dict[str, Any] | None = None,
    include_memcpy: bool = True,
    **kwargs: Any,
) -> Capture:
    """Capture ``fn(*args, **kwargs)`` as a trace.  ``fn`` may already be a
    ``jax.jit``-wrapped function (it is not re-wrapped)."""
    import jax

    jit_kwargs = dict(jit_kwargs or {})
    if static_argnums:
        jit_kwargs["static_argnums"] = tuple(static_argnums)
    if donate_argnums:
        jit_kwargs["donate_argnums"] = tuple(donate_argnums)

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, **jit_kwargs)
    lowered = jitted.lower(*args, **kwargs)
    compiled = lowered.compile()

    hlo_text = compiled.as_text()
    cost = {}
    try:
        raw = compiled.cost_analysis()
        if isinstance(raw, (list, tuple)):
            raw = raw[0] if raw else {}
        cost = {k: float(v) for k, v in (raw or {}).items()
                if isinstance(v, (int, float))}
    except Exception:  # cost analysis is best-effort on some backends
        pass

    mem = {}
    try:
        ma = compiled.memory_analysis()
        for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes", "generated_code_size_in_bytes"):
            if hasattr(ma, attr):
                mem[attr] = int(getattr(ma, attr))
    except Exception:
        pass

    dev = jax.devices()[0]
    trace_device = int(os.environ.get("TPUSIM_TRACE_DEVICE", "0") or 0)
    cap_name = name or getattr(fn, "__name__", None) or "captured"
    cap_name = cap_name.replace("<", "").replace(">", "")

    meta: dict[str, Any] = {
        "capture_name": cap_name,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "num_devices": jax.device_count(),
        "trace_device": trace_device,
        "xla_cost_analysis": cost,
        "memory_analysis": mem,
    }

    in_bytes = _tree_bytes(args) + _tree_bytes(kwargs) if include_memcpy else 0
    out_bytes = 0
    if include_memcpy:
        try:
            import math

            out_shapes = lowered.out_info
            out_bytes = 0
            for s in jax.tree_util.tree_leaves(out_shapes):
                dt = getattr(s, "dtype", None)
                if dt is None:
                    continue
                # newer jax returns OutInfo leaves carrying shape/dtype
                # but no .size — derive the element count from the shape
                # (a scalar's empty shape is 1 element, not 0 bytes)
                size = getattr(s, "size", None)
                if size is None:
                    shape = getattr(s, "shape", None)
                    size = math.prod(shape) if shape is not None else 0
                out_bytes += int(size) * getattr(dt, "itemsize", 0)
        except Exception:
            out_bytes = 0

    return Capture(
        name=cap_name, hlo_text=hlo_text, meta=meta,
        in_bytes=in_bytes, out_bytes=out_bytes,
    )


def capture_to_dir(
    path: str | Path,
    fn: Callable,
    *args: Any,
    name: str | None = None,
    launches: int = 1,
    **kwargs: Any,
) -> TraceDir:
    """Capture and write a trace directory (module + commandlist + meta) —
    the end-to-end ``run_hw_trace.py`` equivalent for one workload."""
    cap = capture(fn, *args, name=name, **kwargs)
    cmds: list[TraceCommand] = []
    for i in range(launches):
        launch_cmds = cap.commands()
        # steady-state shape: inputs uploaded once before the first launch,
        # outputs read back once after the last; middles are kernel-only
        if i > 0:
            launch_cmds = [
                c for c in launch_cmds if c.kind != CommandKind.MEMCPY_H2D
            ]
        if i < launches - 1:
            launch_cmds = [
                c for c in launch_cmds if c.kind != CommandKind.MEMCPY_D2H
            ]
        cmds.extend(launch_cmds)
    return save_trace(
        path, modules={cap.name: cap.hlo_text}, commands=cmds, meta=cap.meta
    )


def snapshot_buffers(
    fn: Callable,
    *args: Any,
    out_dir: str | Path,
    launches: int = 1,
    **kwargs: Any,
) -> list[Path]:
    """Run the program on the live backend and dump every output buffer to
    ``.npy`` files after each launch — the silicon-side state checkpoint
    (rebuild of silicon_checkpoint_tool, ``util/tracer_nvbit/others/
    silicon_checkpoint_tool/checkpoint/checkpoint.cu:196-290``, which
    snapshots all live cuMemAlloc regions after each kernel).  Snapshots
    are the functional ground truth a divergence hunt diffs sim-side
    functional state against."""
    import shutil

    import jax
    import numpy as np

    if any(
        isinstance(leaf, jax.ShapeDtypeStruct)
        for leaf in jax.tree_util.tree_leaves((args, kwargs))
    ):
        raise ValueError(
            "snapshot_buffers needs concrete inputs; this workload has "
            "abstract ShapeDtypeStruct args (AOT capture) — skip --snapshot"
        )
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    out_root = Path(out_dir)
    out_root.mkdir(parents=True, exist_ok=True)
    paths: list[Path] = []

    def _sig(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not all(hasattr(l, "shape") and hasattr(l, "dtype")
                   for l in leaves):
            return None
        return treedef, tuple(
            (tuple(l.shape), str(l.dtype)) for l in leaves
        )

    def _thread(out, cur_args):
        """Feed output subtrees back into structurally matching arg slots
        (e.g. a train step's updated params) so launch i+1 sees launch i's
        carried state — the reference tool snapshots *evolving* state
        after each kernel, and that evolution is exactly what a
        divergence hunt diffs."""
        candidates = [out]
        if isinstance(out, (tuple, list)):
            candidates.extend(out)
        new_args = list(cur_args)
        used: set[int] = set()
        changed = False
        for pos, a in enumerate(new_args):
            sa = _sig(a)
            if sa is None:
                continue
            for ci, cand in enumerate(candidates):
                if ci not in used and _sig(cand) == sa:
                    new_args[pos] = cand
                    used.add(ci)
                    changed = True
                    break
        return tuple(new_args), changed

    def _save(i: int, out) -> list:
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if hasattr(l, "dtype")]
        for j, leaf in enumerate(leaves):
            path = out_root / f"launch{i}_buf{j}.npy"
            np.save(path, np.asarray(jax.device_get(leaf)))
            paths.append(path)
        return leaves

    cur_args = args
    out = jitted(*cur_args, **kwargs)
    n_bufs = len(_save(0, out))
    for i in range(1, launches):
        cur_args, changed = _thread(out, cur_args)
        if not changed:
            # stateless program: launches are identical by jit purity —
            # replicate launch-0 buffers instead of re-executing, and say so
            import warnings

            warnings.warn(
                "snapshot_buffers: no output subtree matches any input; "
                "treating the program as stateless per launch and "
                "replicating launch-0 buffers for launches 1.."
                f"{launches - 1}", stacklevel=2,
            )
            for k in range(i, launches):
                for j in range(n_bufs):
                    src = out_root / f"launch0_buf{j}.npy"
                    dst = out_root / f"launch{k}_buf{j}.npy"
                    dst.unlink(missing_ok=True)
                    try:
                        os.link(src, dst)
                    except OSError:
                        shutil.copyfile(src, dst)
                    paths.append(dst)
            break
        out = jitted(*cur_args, **kwargs)
        _save(i, out)
    return paths


def measure_wall_time(
    fn: Callable,
    *args: Any,
    iters: int = 10,
    warmup: int = 3,
    **kwargs: Any,
) -> dict[str, float]:
    """Time real execution — the silicon truth for correlation, standing in
    for nvprof ``Duration`` (``util/plotting/correl_mappings.py:24-100``).

    Timing protocol: on tunneled/remote TPU backends ``block_until_ready``
    can return before device compute finishes (observed on axon), so each
    timed batch is fenced by a 1-element host readback of a reduction over
    the last output — the only reliable sync.  The readback+reduction
    overhead is measured separately on an already-computed buffer and
    subtracted."""
    import jax
    import jax.numpy as jnp

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)

    def _fence(out) -> float:
        # reduce to one scalar and pull it to host: forces full execution
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if hasattr(l, "dtype")]
        acc = sum(jnp.sum(jnp.abs(l.astype(jnp.float32))) for l in leaves)
        return float(acc)

    out = None
    for _ in range(max(warmup, 1)):
        out = jitted(*args, **kwargs)
    _fence(out)

    # fence overhead on a ready output (launches the small reduction again);
    # take the min of a few samples — RPC jitter is large on tunnels
    fence_samples = []
    for _ in range(3):
        f0 = time.perf_counter()
        _fence(out)
        fence_samples.append(time.perf_counter() - f0)
    fence_s = min(fence_samples)

    # size the timed batch so device compute dwarfs fence jitter
    t0 = time.perf_counter()
    out = jitted(*args, **kwargs)
    _fence(out)
    t_one = max(time.perf_counter() - t0 - fence_s, 1e-6)
    target = max(10.0 * fence_s, 0.3)
    batch = max(min(int(target / t_one) + 1, 10_000), max(iters, 1))

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(batch):
            out = jitted(*args, **kwargs)
        _fence(out)
        dt = time.perf_counter() - t0
        times.append(max(dt - fence_s, 1e-9) / batch)
    times.sort()
    return {
        "iters": float(3 * batch),
        "fence_s": fence_s,
        "min_s": times[0],
        "median_s": times[len(times) // 2],
        "mean_s": sum(times) / len(times),
    }
